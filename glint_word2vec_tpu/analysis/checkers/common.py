"""Shared AST helpers for the graftlint checkers."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple


def dotted_name(node: ast.AST) -> Optional[str]:
    """``np.save`` / ``self._tick_tables`` / ``open`` as a dotted string,
    or None for anything that is not a plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an expression chain: the root of
    ``os.environ.get(...)[0]`` is ``os``."""
    while True:
        if isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


def walk_functions(
    tree: ast.AST,
) -> Iterator[Tuple[str, ast.AST]]:
    """Yield ``(qualname, node)`` for every function/method, including
    nested ones (``Class.method.<locals>.inner`` style collapsed to
    ``Class.method.inner``)."""

    def rec(node: ast.AST, prefix: str) -> Iterator[Tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                yield qn, child
                yield from rec(child, qn + ".")
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.")
            else:
                yield from rec(child, prefix)

    yield from rec(tree, "")


def enclosing_map(tree: ast.AST) -> dict:
    """Map id(node) -> qualname of the innermost enclosing function for
    every node in the tree ('' at module level)."""
    out: dict = {}

    def rec(node: ast.AST, prefix: str, fn: str) -> None:
        out[id(node)] = fn
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                rec(child, qn + ".", qn)
            elif isinstance(child, ast.ClassDef):
                rec(child, f"{prefix}{child.name}.", fn)
            else:
                rec(child, prefix, fn)

    rec(tree, "", "")
    return out


def assign_target_attrs(node: ast.AST) -> List[ast.Attribute]:
    """``self.x`` attribute targets of an assignment statement,
    flattening tuple/list unpacking."""
    targets: List[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    out: List[ast.Attribute] = []
    stack = targets
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Attribute):
            out.append(t)
    return out


def is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and (attr is None or node.attr == attr)
    )


def literal_str_collection(node: ast.AST) -> Optional[List[str]]:
    """Statically evaluate a set/frozenset/tuple/list of string
    literals; None when the expression is anything else."""
    if isinstance(node, ast.Call) and call_name(node) in ("frozenset", "set",
                                                         "tuple", "list"):
        if len(node.args) != 1 or node.keywords:
            return None
        node = node.args[0]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in node.elts:
            s = const_str(e)
            if s is None:
                return None
            out.append(s)
        return out
    return None
