"""The built-in graftlint checkers. Importing this package registers
every rule with :data:`glint_word2vec_tpu.analysis.core.CHECKERS`."""

from glint_word2vec_tpu.analysis.checkers import (  # noqa: F401
    atomic_persist,
    fault_points,
    lock_discipline,
    prometheus,
    span_registry,
    sync_point,
    table_mutation,
)
