// host_ops: native host-side data-path kernels for glint_word2vec_tpu.
//
// The reference keeps its host data path on the JVM (Spark RDD passes); its
// only native touchpoints are netlib BLAS and Aeron shared memory (SURVEY.md
// §2.1 native-code census). In the TPU build the host data path must feed a
// chip at millions of words/sec, so the measured hot spots live here
// (the corpus scanner at the bottom of this file is the third):
//
//   1. alias_build    — O(V) Walker alias-table construction (the Python
//                       two-pointer loop takes minutes at 10M vocab).
//   2. window_batch   — per-epoch subsample + shrunk-window context/mask
//                       generation, thread-parallel across sentence
//                       chunks (output invariant to thread count).
//                       Measured numbers live in HOSTPATH.json
//                       (scripts/host_path_bench.py): ~15M center
//                       positions/s single-threaded on the 1-core build
//                       host vs ~0.85M/s for the Python fallback;
//                       multi-core feeder hosts scale with threads.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
// All buffers are caller-allocated NumPy arrays; nothing here allocates
// Python objects or touches the GIL, so callers may release it.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

#if defined(__unix__) || defined(__APPLE__)
#define GLINT_HAVE_MMAP 1
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#endif

extern "C" {

// Walker/Vose alias table over `weights[0..n)`. Outputs:
//   prob[i]  in [0,1]  — acceptance probability for column i
//   alias[i] in [0,n)  — fallback index for column i
// Matches the Python reference implementation in corpus/alias.py (tested
// for distribution equality). Returns 0 on success, nonzero on bad input.
int alias_build(const double* weights, int64_t n, float* prob, int32_t* alias) {
    if (n <= 0) return 1;
    double total = 0.0;
    for (int64_t i = 0; i < n; ++i) {
        double w = weights[i];
        if (!(w >= 0.0) || w != w) return 2;  // negative or NaN
        total += w;
    }
    if (!(total > 0.0)) return 3;

    std::vector<double> scaled(n);
    const double k = static_cast<double>(n) / total;
    for (int64_t i = 0; i < n; ++i) scaled[i] = weights[i] * k;

    // Two-pointer partition: indices of small (<1) and large (>=1) columns.
    std::vector<int64_t> small, large;
    small.reserve(n);
    large.reserve(n);
    for (int64_t i = 0; i < n; ++i) {
        prob[i] = 1.0f;
        alias[i] = static_cast<int32_t>(i);
        (scaled[i] < 1.0 ? small : large).push_back(i);
    }
    while (!small.empty() && !large.empty()) {
        int64_t s = small.back();
        small.pop_back();
        int64_t l = large.back();
        large.pop_back();
        prob[s] = static_cast<float>(scaled[s]);
        alias[s] = static_cast<int32_t>(l);
        scaled[l] = (scaled[l] + scaled[s]) - 1.0;
        if (scaled[l] < 1.0) small.push_back(l); else large.push_back(l);
    }
    return 0;
}

// xorshift128+ PRNG — fast, well-distributed, deterministic per seed.
struct Rng {
    uint64_t s0, s1;
    explicit Rng(uint64_t seed) {
        // splitmix64 seeding
        auto next = [&seed]() {
            seed += 0x9E3779B97f4A7C15ULL;
            uint64_t z = seed;
            z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
            z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
            return z ^ (z >> 31);
        };
        s0 = next();
        s1 = next();
    }
    inline uint64_t next_u64() {
        uint64_t x = s0;
        const uint64_t y = s1;
        s0 = y;
        x ^= x << 23;
        s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
        return s1 + y;
    }
    // uniform double in [0, 1)
    inline double next_double() {
        return (next_u64() >> 11) * (1.0 / 9007199254740992.0);
    }
    // uniform int in [0, m)
    inline int64_t next_below(int64_t m) {
        return static_cast<int64_t>(next_u64() % static_cast<uint64_t>(m));
    }
};

}  // extern "C"

// One epoch pass over a flattened corpus: frequency subsampling + shrunk-
// window context generation, emitting fixed-width rows. Parallelized
// across sentences with a deterministic two-phase scheme — per-sentence
// PRNG seeds make the output BYTE-IDENTICAL for every thread count
// (phase 1 counts kept rows per sentence chunk, a prefix sum fixes each
// chunk's output offset, phase 2 re-derives the same draws and fills).
//
// Inputs:
//   ids        — concatenated sentence word-indices, int32[total_len]
//   offsets    — sentence boundaries, int64[n_sentences+1]
//   keep_prob  — per-word keep probability, float32[vocab] (all-1 disables)
//   window     — reference windowSize; per position draw b in [0, window)
//                and take offsets [-b, b-1] \ {0} (mllib:384-388)
//   seed       — epoch seed (caller mixes epoch index)
//   threads    — worker count; <=0 picks hardware_concurrency
// Outputs (caller-allocated, capacity rows >= total_len):
//   centers    — int32[capacity]
//   contexts   — int32[capacity * ctx_width]   (ctx_width = 2*window - 3,
//                matching corpus.batching.context_width; zero-padded)
//   mask       — float32[capacity * ctx_width]
// Returns the number of rows written (= number of kept word positions), or
// -1 if capacity was insufficient.

namespace {

inline uint64_t sentence_seed(uint64_t seed, int64_t s) {
    // splitmix64 over (seed, sentence index): independent per-sentence
    // streams, stable across thread counts.
    uint64_t z = seed + 0x9E3779B97f4A7C15ULL * static_cast<uint64_t>(s + 1);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

// Number of kept positions in sentence s (phase-1 counting: consumes the
// same subsample draws phase 2 will).
inline int64_t count_kept(const int32_t* ids, int64_t beg, int64_t end,
                          const float* keep_prob, uint64_t sseed) {
    Rng rng(sseed);
    int64_t kept = 0;
    for (int64_t i = beg; i < end; ++i) {
        const float kp = keep_prob[ids[i]];
        if (kp >= 1.0f || rng.next_double() <= kp) ++kept;
    }
    return kept;
}

// Fill rows for sentence s starting at output row `row`; returns rows
// written. Draw order matches count_kept: all subsample draws first,
// then one b draw per kept position.
inline int64_t fill_sentence(const int32_t* ids, int64_t beg, int64_t end,
                             const float* keep_prob, uint64_t sseed,
                             int64_t W, int64_t C, int64_t row,
                             int32_t* centers, int32_t* contexts,
                             float* mask, std::vector<int32_t>& kept) {
    Rng rng(sseed);
    kept.clear();
    for (int64_t i = beg; i < end; ++i) {
        const int32_t w = ids[i];
        const float kp = keep_prob[w];
        if (kp >= 1.0f || rng.next_double() <= kp) kept.push_back(w);
    }
    const int64_t L = static_cast<int64_t>(kept.size());
    for (int64_t i = 0; i < L; ++i) {
        const int64_t b = (W > 0) ? rng.next_below(W) : 0;  // [0, W)
        centers[row] = kept[static_cast<size_t>(i)];
        int32_t* ctx = contexts + row * C;
        float* m = mask + row * C;
        std::memset(ctx, 0, sizeof(int32_t) * C);
        std::memset(m, 0, sizeof(float) * C);
        // context positions [max(0,i-b), min(i+b,L)) excluding i;
        // lane layout matches corpus.batching.window_offsets:
        // lanes [0, W-1) hold offsets -(W-1)..-1, lanes [W-1, C) hold
        // offsets 1..W-2.
        const int64_t lo = (i - b) > 0 ? (i - b) : 0;
        const int64_t hi = (i + b) < L ? (i + b) : L;
        for (int64_t j = lo; j < hi; ++j) {
            if (j == i) continue;
            const int64_t off = j - i;  // in [-(W-1), W-2], != 0
            const int64_t lane = off < 0 ? off + (W - 1) : (W - 1) + off - 1;
            ctx[lane] = kept[static_cast<size_t>(j)];
            m[lane] = 1.0f;
        }
        ++row;
    }
    return L;
}

}  // namespace

extern "C" {

int64_t window_batch_epoch(
    const int32_t* ids, const int64_t* offsets, int64_t n_sentences,
    const float* keep_prob, int32_t window, uint64_t seed,
    int32_t* centers, int32_t* contexts, float* mask,
    int64_t capacity, int64_t* words_done_out, int32_t threads) {
    const int64_t W = window;
    const int64_t C = (2 * W - 3) > 1 ? (2 * W - 3) : 1;
    int64_t T = threads > 0
                    ? threads
                    : static_cast<int64_t>(std::thread::hardware_concurrency());
    if (T < 1) T = 1;
    if (T > n_sentences) T = n_sentences > 0 ? n_sentences : 1;

    // Contiguous sentence chunks balanced by word count, not sentence
    // count (sentence lengths vary).
    const int64_t total_words = n_sentences > 0 ? offsets[n_sentences] : 0;
    std::vector<int64_t> chunk_begin(T + 1, n_sentences);
    chunk_begin[0] = 0;
    for (int64_t t = 1; t < T; ++t) {
        const int64_t target = total_words * t / T;
        chunk_begin[t] = std::lower_bound(offsets, offsets + n_sentences + 1,
                                          target) -
                         offsets;
        if (chunk_begin[t] > n_sentences) chunk_begin[t] = n_sentences;
        if (chunk_begin[t] < chunk_begin[t - 1])
            chunk_begin[t] = chunk_begin[t - 1];
    }
    chunk_begin[T] = n_sentences;

    // Runs fn(0..T-1): T-1 spawned workers, the last chunk on the caller
    // thread. If pthread creation fails mid-loop (thread rlimit, EAGAIN),
    // the unspawned chunks simply run inline — never std::terminate via
    // a joinable-thread destructor.
    auto run_parallel = [&](auto&& fn) {
        std::vector<std::thread> pool;
        pool.reserve(T > 0 ? T - 1 : 0);
        int64_t spawned = 0;
        try {
            for (int64_t t = 0; t + 1 < T; ++t) {
                pool.emplace_back(fn, t);
                ++spawned;
            }
        } catch (...) {
            // degrade below: chunks [spawned, T) run on this thread
        }
        for (int64_t t = spawned; t < T; ++t) fn(t);
        for (auto& th : pool) th.join();
    };

    // Phase 1: kept-row count per chunk.
    std::vector<int64_t> chunk_rows(T, 0);
    auto count_chunk = [&](int64_t t) {
        int64_t rows = 0;
        for (int64_t s = chunk_begin[t]; s < chunk_begin[t + 1]; ++s)
            rows += count_kept(ids, offsets[s], offsets[s + 1], keep_prob,
                               sentence_seed(seed, s));
        chunk_rows[t] = rows;
    };
    run_parallel(count_chunk);
    std::vector<int64_t> chunk_start(T + 1, 0);
    for (int64_t t = 0; t < T; ++t)
        chunk_start[t + 1] = chunk_start[t] + chunk_rows[t];
    if (chunk_start[T] > capacity) return -1;

    // Phase 2: fill — each chunk writes its own disjoint row range.
    auto fill_chunk = [&](int64_t t) {
        std::vector<int32_t> kept;
        int64_t row = chunk_start[t];
        for (int64_t s = chunk_begin[t]; s < chunk_begin[t + 1]; ++s)
            row += fill_sentence(ids, offsets[s], offsets[s + 1], keep_prob,
                                 sentence_seed(seed, s), W, C, row, centers,
                                 contexts, mask, kept);
    };
    run_parallel(fill_chunk);

    if (words_done_out) *words_done_out = total_words;
    return chunk_start[T];
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native corpus scanner: the fit_file() ingestion passes (vocab count +
// flat int32 encode) that were the end-to-end wall-clock dominator in pure
// Python (per-token dict lookups measure ~1M words/s; the 50M-word
// fit_file_bench attempt spent ~27 min in host prep). Reference analogue:
// learnVocab's flatMap->reduceByKey (mllib:258-279) and the words->indices
// map (mllib:335-343), which the reference runs on the JVM across a Spark
// cluster; one host feeding a TPU chip needs the same passes at native
// speed on one core.
//
// Tokenization matches Python's text pipeline (iter_text_file /
// encode_file: universal-newline line iteration + str.split()) for every
// valid-UTF-8 corpus: separators are the full str.split() whitespace set
// (ASCII \t-\r, \x1c-\x1f, space, plus Unicode NEL/NBSP/U+1680/
// U+2000-200A/U+2028/U+2029/U+202F/U+205F/U+3000), and a sentence ends at
// '\n' or '\r' ('\r\n' yields one empty extra line, which is dropped —
// exactly universal-newline behavior). Blocks are re-aligned so UTF-8
// sequences never straddle a read boundary. Anything the byte-level pass
// cannot reproduce exactly — invalid UTF-8 (Python decodes with
// errors='replace', merging tokens that differ only in invalid bytes) or
// a requested Unicode-aware lowercase — is NOT handled here: corpus_open
// fails (or the wrapper declines) and the caller falls back to the Python
// path, so the two paths can never silently diverge.
//
// Single-read design: the one counting pass also records the token stream
// as provisional first-seen ids (4 bytes per corpus word, transient), so
// corpus_encode is a hash-free linear remap instead of a second file read
// + 1 hash lookup per word (measured 1.7s/5M words; the remap is ~0.1s).

namespace {

// Single-byte (ASCII) whitespace, the str.split() subset below 0x80.
// Shared by sep_len AND the parallel chunk-boundary search: boundaries
// may only land on bytes BOTH agree are separators, or a token could be
// silently split across chunks.
inline bool is_ascii_ws(unsigned char c) {
    return c == ' ' || (c >= 0x09 && c <= 0x0d) || (c >= 0x1c && c <= 0x1f);
}

// Byte length of the whitespace separator starting at p (sequences are
// block-complete by construction), or 0 if p starts a token byte.
// *line_end_out: '\n' / '\r' — universal-newline sentence boundaries.
inline size_t sep_len(const unsigned char* p, size_t rem,
                      bool* line_end_out) {
    const unsigned char c = p[0];
    *line_end_out = (c == '\n' || c == '\r');
    if (*line_end_out) return 1;
    if (is_ascii_ws(c)) return 1;
    if (c < 0x80) return 0;
    if (c == 0xC2 && rem >= 2 && (p[1] == 0x85 || p[1] == 0xA0))
        return 2;  // U+0085 NEL, U+00A0 NBSP
    if (c == 0xE1 && rem >= 3 && p[1] == 0x9A && p[2] == 0x80)
        return 3;  // U+1680
    if (c == 0xE2 && rem >= 3) {
        if (p[1] == 0x80 && ((p[2] >= 0x80 && p[2] <= 0x8A) ||
                             p[2] == 0xA8 || p[2] == 0xA9 || p[2] == 0xAF))
            return 3;  // U+2000-200A, U+2028, U+2029, U+202F
        if (p[1] == 0x81 && p[2] == 0x9F) return 3;  // U+205F
    }
    if (c == 0xE3 && rem >= 3 && p[1] == 0x80 && p[2] == 0x80)
        return 3;  // U+3000
    return 0;
}

// Strict UTF-8 validity (RFC 3629: no overlongs, no surrogates, <= U+10FFFF).
bool valid_utf8(const char* s, size_t n) {
    const unsigned char* p = reinterpret_cast<const unsigned char*>(s);
    size_t i = 0;
    while (i < n) {
        const unsigned char c = p[i];
        if (c < 0x80) { ++i; continue; }
        size_t len;
        unsigned char lo = 0x80, hi = 0xBF;
        if (c >= 0xC2 && c <= 0xDF) len = 2;
        else if (c == 0xE0) { len = 3; lo = 0xA0; }
        else if (c >= 0xE1 && c <= 0xEC) len = 3;
        else if (c == 0xED) { len = 3; hi = 0x9F; }
        else if (c >= 0xEE && c <= 0xEF) len = 3;
        else if (c == 0xF0) { len = 4; lo = 0x90; }
        else if (c >= 0xF1 && c <= 0xF3) len = 4;
        else if (c == 0xF4) { len = 4; hi = 0x8F; }
        else return false;
        if (i + len > n) return false;
        if (p[i + 1] < lo || p[i + 1] > hi) return false;
        for (size_t k = 2; k < len; ++k)
            if (p[i + k] < 0x80 || p[i + k] > 0xBF) return false;
        i += len;
    }
    return true;
}

// Bytes at the end of [p, p+n) belonging to a possibly-incomplete UTF-8
// sequence, to roll over into the next read block (0..3).
size_t utf8_tail(const char* s, size_t n) {
    if (n == 0) return 0;
    const unsigned char* p = reinterpret_cast<const unsigned char*>(s);
    size_t i = n, back = 0;
    while (i > 0 && back < 3 && (p[i - 1] & 0xC0) == 0x80) { --i; ++back; }
    if (i == 0) return 0;  // all continuation bytes: invalid, caught later
    const unsigned char lead = p[i - 1];
    const size_t len = lead < 0x80 ? 1
                       : lead >= 0xF0 ? 4
                       : lead >= 0xE0 ? 3
                       : lead >= 0xC0 ? 2 : 1;
    const size_t have = n - (i - 1);
    return len > have ? have : 0;
}

struct Ent {
    int64_t count;
    int64_t first;  // first-occurrence order key: the count-desc tiebreak
};

struct Corpus {
    std::string path;
    // Unified post-count vocab store, indexed by gid (assigned in a
    // deterministic first-occurrence order): words[gid] are string_views
    // into `tab` keys (streaming path) or the mmap (parallel path) —
    // both stable for the handle's lifetime.
    std::vector<std::string_view> words;
    std::vector<Ent> ents;
    std::unordered_map<std::string, int64_t> tab;  // streaming byte owner
    char* map_base = nullptr;  // parallel-path byte owner
    size_t map_len = 0;
    // Token stream as gids + raw line lengths, recorded during the
    // counting pass; freed by corpus_encode (one-shot).
    std::vector<int32_t> prov;
    std::vector<int64_t> prov_lens;
    bool prov_consumed = false;
    // Sorted vocab cache for the min_count last queried.
    int64_t cached_min = -1;
    std::vector<int64_t> sorted_gids;
    // Encode results.
    std::vector<int32_t> enc_ids;
    std::vector<int64_t> enc_lens;

    // Every delete path (including the invalid-UTF-8 bail in corpus_open)
    // must release the mapping, so it lives in the destructor.
    ~Corpus() {
#ifdef GLINT_HAVE_MMAP
        if (map_base) munmap(map_base, map_len);
#endif
    }
};

// Streams `path` in ~1 MiB UTF-8-aligned blocks, calling token(ptr, len)
// for each token (never spanning calls; partial tokens carry across block
// boundaries) and line_end() at every '\n'/'\r'. Returns false on open or
// read error, or when token() returns false (abort request).
template <typename TokenFn, typename LineFn>
bool scan_file(const std::string& path, TokenFn&& token, LineFn&& line_end) {
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) return false;
    constexpr size_t BLK = 1 << 20;
    std::vector<char> buf(BLK + 4);
    std::string carry;
    size_t pre = 0;  // rolled-over incomplete UTF-8 tail from last block
    auto emit = [&](const char* p, size_t n) -> bool {
        if (carry.empty()) return token(p, n);
        carry.append(p, n);
        bool ok = token(carry.data(), carry.size());
        carry.clear();
        return ok;
    };
    for (;;) {
        const size_t got = std::fread(buf.data() + pre, 1, BLK, f);
        if (got == 0) break;
        size_t avail = pre + got;
        const size_t keep = utf8_tail(buf.data(), avail);
        avail -= keep;
        size_t i = 0;
        while (i < avail) {
            bool is_line;
            const size_t sl = sep_len(
                reinterpret_cast<unsigned char*>(buf.data()) + i, avail - i,
                &is_line);
            if (sl) {
                if (!carry.empty()) {
                    if (!token(carry.data(), carry.size())) {
                        std::fclose(f);
                        return false;
                    }
                    carry.clear();
                }
                if (is_line) line_end();
                i += sl;
                continue;
            }
            size_t j = i;
            bool dummy;
            while (j < avail &&
                   sep_len(reinterpret_cast<unsigned char*>(buf.data()) + j,
                           avail - j, &dummy) == 0)
                ++j;
            if (j < avail) {
                if (!emit(buf.data() + i, j - i)) {
                    std::fclose(f);
                    return false;
                }
            } else {
                carry.append(buf.data() + i, j - i);  // may continue
            }
            i = j;
        }
        std::memmove(buf.data(), buf.data() + avail, keep);
        pre = keep;
    }
    const bool read_error = std::ferror(f) != 0;
    std::fclose(f);
    if (read_error) return false;
    if (pre) carry.append(buf.data(), pre);  // incomplete tail at EOF
    if (!carry.empty() && !token(carry.data(), carry.size())) return false;
    line_end();  // final line without trailing newline
    return true;
}

void ensure_sorted(Corpus* c, int64_t min_count) {
    if (c->cached_min == min_count) return;
    c->sorted_gids.clear();
    c->sorted_gids.reserve(c->ents.size());
    for (int64_t g = 0; g < static_cast<int64_t>(c->ents.size()); ++g) {
        if (c->ents[static_cast<size_t>(g)].count >= min_count)
            c->sorted_gids.push_back(g);
    }
    std::sort(c->sorted_gids.begin(), c->sorted_gids.end(),
              [c](int64_t a, int64_t b) {
                  const Ent& ea = c->ents[static_cast<size_t>(a)];
                  const Ent& eb = c->ents[static_cast<size_t>(b)];
                  if (ea.count != eb.count) return ea.count > eb.count;
                  return ea.first < eb.first;
              });
    c->cached_min = min_count;
}

inline bool token_utf8_ok(const char* p, size_t n) {
    bool ascii = true;
    for (size_t k = 0; k < n; ++k)
        if (static_cast<unsigned char>(p[k]) >= 0x80) {
            ascii = false;
            break;
        }
    return ascii || valid_utf8(p, n);
}

}  // namespace

namespace {

// Streaming (fread-based) counting pass: fills the unified vocab store
// sequentially. Used for small files, threads==1, or when mmap is
// unavailable. Returns false on I/O error or invalid UTF-8.
bool count_streaming(Corpus* c) {
    c->tab.reserve(1 << 20);
    int64_t line_start = 0;
    return scan_file(
        c->path,
        [&](const char* p, size_t n) -> bool {
            if (!token_utf8_ok(p, n)) return false;
            auto [it, inserted] = c->tab.try_emplace(
                std::string(p, n),
                static_cast<int64_t>(c->words.size()));
            const int64_t gid = it->second;
            if (inserted) {
                c->words.emplace_back(it->first);
                c->ents.push_back(Ent{1, gid});
            } else {
                ++c->ents[static_cast<size_t>(gid)].count;
            }
            c->prov.push_back(static_cast<int32_t>(gid));
            return true;
        },
        [&] {
            c->prov_lens.push_back(
                static_cast<int64_t>(c->prov.size()) - line_start);
            line_start = static_cast<int64_t>(c->prov.size());
        });
}

#ifdef GLINT_HAVE_MMAP

// Parallel counting pass over an mmap'd file: contiguous byte chunks
// split at ASCII whitespace (so neither tokens nor multi-byte Unicode
// separators straddle a boundary), each scanned into a chunk-local
// vocab, then a deterministic sequential merge assigns gids in global
// first-occurrence order — the output (words/ents/prov/prov_lens) is
// byte-identical to the streaming pass for every thread count.
struct ChunkScan {
    std::unordered_map<std::string_view, int32_t> lmap;
    std::vector<std::string_view> lwords;
    std::vector<int64_t> lcounts;
    std::vector<int64_t> lfirst;   // chunk-local token index of 1st occur.
    std::vector<int32_t> lprov;    // local ids per token
    std::vector<int64_t> lbreaks;  // local token count at each line end
    bool bad = false;
};

void scan_chunk(const char* base, size_t beg, size_t end, ChunkScan* out) {
    size_t i = beg;
    while (i < end) {
        bool is_line;
        const size_t sl =
            sep_len(reinterpret_cast<const unsigned char*>(base) + i,
                    end - i, &is_line);
        if (sl) {
            if (is_line)
                out->lbreaks.push_back(
                    static_cast<int64_t>(out->lprov.size()));
            i += sl;
            continue;
        }
        size_t j = i;
        bool dummy;
        while (j < end &&
               sep_len(reinterpret_cast<const unsigned char*>(base) + j,
                       end - j, &dummy) == 0)
            ++j;
        if (!token_utf8_ok(base + i, j - i)) {
            out->bad = true;
            return;
        }
        std::string_view w(base + i, j - i);
        auto [it, inserted] = out->lmap.try_emplace(
            w, static_cast<int32_t>(out->lwords.size()));
        const int32_t lid = it->second;
        if (inserted) {
            out->lwords.push_back(w);
            out->lcounts.push_back(1);
            out->lfirst.push_back(
                static_cast<int64_t>(out->lprov.size()));
        } else {
            ++out->lcounts[static_cast<size_t>(lid)];
        }
        out->lprov.push_back(lid);
        i = j;
    }
}

// Returns true on success; false = caller should fall back to streaming
// (mmap failure) — invalid UTF-8 instead reports *invalid=true.
bool count_parallel(Corpus* c, int64_t threads, bool* invalid) {
    int fd = ::open(c->path.c_str(), O_RDONLY);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size <= 0) {
        ::close(fd);
        return false;
    }
    const size_t n = static_cast<size_t>(st.st_size);
    void* m = mmap(nullptr, n, PROT_READ, MAP_PRIVATE, fd, 0);
    ::close(fd);
    if (m == MAP_FAILED) return false;
    c->map_base = static_cast<char*>(m);
    c->map_len = n;
    const char* base = c->map_base;

    // >=8 MiB per chunk: below that, thread + merge overhead dominates.
    // GLINT_NATIVE_CHUNK_BYTES overrides (tests use a tiny floor so the
    // multi-chunk merge is exercised on small fixtures).
    size_t chunk_floor = 8u << 20;
    if (const char* e = std::getenv("GLINT_NATIVE_CHUNK_BYTES")) {
        char* endp = nullptr;
        const unsigned long long v = std::strtoull(e, &endp, 10);
        if (endp && *endp == '\0' && v > 0) chunk_floor = v;
    }
    int64_t T = threads;
    const int64_t by_size = static_cast<int64_t>(n / chunk_floor) + 1;
    if (T > by_size) T = by_size;
    if (T < 1) T = 1;

    std::vector<size_t> bound(T + 1, n);
    bound[0] = 0;
    for (int64_t t = 1; t < T; ++t) {
        size_t p = n * static_cast<size_t>(t) / static_cast<size_t>(T);
        if (p < bound[t - 1]) p = bound[t - 1];
        while (p < n && !is_ascii_ws(static_cast<unsigned char>(base[p])))
            ++p;
        bound[t] = p;
    }

    std::vector<ChunkScan> chunks(static_cast<size_t>(T));
    {
        std::vector<std::thread> pool;
        pool.reserve(static_cast<size_t>(T > 0 ? T - 1 : 0));
        int64_t spawned = 0;
        try {
            for (int64_t t = 0; t + 1 < T; ++t) {
                pool.emplace_back(scan_chunk, base, bound[t], bound[t + 1],
                                  &chunks[static_cast<size_t>(t)]);
                ++spawned;
            }
        } catch (...) {
        }
        for (int64_t t = spawned; t < T; ++t)
            scan_chunk(base, bound[t], bound[t + 1],
                       &chunks[static_cast<size_t>(t)]);
        for (auto& th : pool) th.join();
    }
    for (const auto& ch : chunks)
        if (ch.bad) {
            *invalid = true;
            return true;  // handled: caller reports invalid UTF-8
        }

    // Chunk token offsets.
    std::vector<int64_t> tok_off(T + 1, 0);
    for (int64_t t = 0; t < T; ++t)
        tok_off[t + 1] =
            tok_off[t] +
            static_cast<int64_t>(chunks[static_cast<size_t>(t)].lprov.size());

    // Deterministic merge: chunks in order, words within a chunk in
    // first-occurrence order -> gids follow global first occurrence.
    std::unordered_map<std::string_view, int64_t> gmap;
    std::vector<std::vector<int32_t>> luts(static_cast<size_t>(T));
    for (int64_t t = 0; t < T; ++t) {
        auto& ch = chunks[static_cast<size_t>(t)];
        auto& lut = luts[static_cast<size_t>(t)];
        lut.resize(ch.lwords.size());
        for (size_t l = 0; l < ch.lwords.size(); ++l) {
            auto [it, inserted] = gmap.try_emplace(
                ch.lwords[l], static_cast<int64_t>(c->words.size()));
            const int64_t gid = it->second;
            if (inserted) {
                c->words.push_back(ch.lwords[l]);
                c->ents.push_back(Ent{ch.lcounts[l],
                                      tok_off[t] + ch.lfirst[l]});
            } else {
                c->ents[static_cast<size_t>(gid)].count += ch.lcounts[l];
            }
            lut[l] = static_cast<int32_t>(gid);
        }
        ch.lmap.clear();
    }

    // Global prov stream: parallel per-chunk remap into disjoint ranges.
    // Each chunk releases its local stream + lut the moment it is
    // remapped, so peak memory stays ~one token stream plus the largest
    // in-flight chunk set, not 2x the corpus.
    c->prov.resize(static_cast<size_t>(tok_off[T]));
    auto remap_chunk = [&](int64_t t) {
        auto& ch = chunks[static_cast<size_t>(t)];
        auto& lut = luts[static_cast<size_t>(t)];
        int32_t* out = c->prov.data() + tok_off[t];
        for (size_t i = 0; i < ch.lprov.size(); ++i)
            out[i] = lut[static_cast<size_t>(ch.lprov[i])];
        std::vector<int32_t>().swap(ch.lprov);
        std::vector<int32_t>().swap(lut);
    };
    {
        std::vector<std::thread> pool;
        pool.reserve(static_cast<size_t>(T > 0 ? T - 1 : 0));
        int64_t spawned = 0;
        try {
            for (int64_t t = 0; t + 1 < T; ++t) {
                pool.emplace_back(remap_chunk, t);
                ++spawned;
            }
        } catch (...) {
        }
        for (int64_t t = spawned; t < T; ++t) remap_chunk(t);
        for (auto& th : pool) th.join();
    }

    // Line lengths: merged break positions + the EOF line end.
    int64_t prev = 0;
    for (int64_t t = 0; t < T; ++t) {
        for (int64_t lb : chunks[static_cast<size_t>(t)].lbreaks) {
            c->prov_lens.push_back(tok_off[t] + lb - prev);
            prev = tok_off[t] + lb;
        }
    }
    c->prov_lens.push_back(tok_off[T] - prev);
    return true;
}

#endif  // GLINT_HAVE_MMAP

}  // namespace

extern "C" {

// Opens `path` and runs the counting pass — thread-parallel over mmap'd
// byte chunks when `threads` allows (output identical to the sequential
// pass for every thread count), streaming otherwise. Returns a handle
// (free with corpus_free), or nullptr if the file can't be read OR
// contains invalid UTF-8 (the caller then uses the Python path, whose
// errors='replace' decode semantics a byte-level pass cannot reproduce).
// threads: <=0 picks hardware_concurrency; 1 forces the streaming pass.
void* corpus_open(const char* path, int32_t threads) {
    auto* c = new Corpus;
    c->path = path;
    int64_t T = threads > 0
                    ? threads
                    : static_cast<int64_t>(
                          std::thread::hardware_concurrency());
    if (T < 1) T = 1;
    bool ok = false;
#ifdef GLINT_HAVE_MMAP
    if (T > 1) {
        bool invalid = false;
        if (count_parallel(c, T, &invalid)) {
            if (invalid) {
                delete c;
                return nullptr;
            }
            return c;
        }
        // mmap unavailable (pipe, empty file, ...): stream instead.
    }
#endif
    ok = count_streaming(c);
    if (!ok) {
        delete c;
        return nullptr;
    }
    return c;
}

int64_t corpus_vocab_size(void* h, int64_t min_count) {
    auto* c = static_cast<Corpus*>(h);
    ensure_sorted(c, min_count);
    return static_cast<int64_t>(c->sorted_gids.size());
}

int64_t corpus_vocab_chars(void* h, int64_t min_count) {
    auto* c = static_cast<Corpus*>(h);
    ensure_sorted(c, min_count);
    int64_t total = 0;
    for (int64_t g : c->sorted_gids)
        total += static_cast<int64_t>(c->words[static_cast<size_t>(g)].size());
    return total;
}

// Fills caller-allocated buffers with the vocab sorted by (count desc,
// first-seen asc): `chars` = concatenated UTF-8 word bytes, `offs`
// (int64[n+1]) word boundaries within it, `counts` (int64[n]).
int corpus_vocab_fill(void* h, int64_t min_count, char* chars, int64_t* offs,
                      int64_t* counts) {
    auto* c = static_cast<Corpus*>(h);
    ensure_sorted(c, min_count);
    int64_t pos = 0, i = 0;
    offs[0] = 0;
    for (int64_t g : c->sorted_gids) {
        const std::string_view w = c->words[static_cast<size_t>(g)];
        std::memcpy(chars + pos, w.data(), w.size());
        pos += static_cast<int64_t>(w.size());
        counts[i] = c->ents[static_cast<size_t>(g)].count;
        offs[++i] = pos;
    }
    return 0;
}

// "Encode" = hash-free linear remap of the recorded provisional-id stream:
// ids become frequency ranks for the given min_count, OOV dropped,
// sentences = lines chunked at max_sentence_length, empty sentences
// dropped. Returns the total id count (query sentence count via
// *n_sentences_out), or -1 on bad input.
//
// ONE-SHOT per handle: the provisional stream (4 B/corpus word) is freed
// here — its last use — so the handle never holds the provisional stream,
// the encode output, and the hashmap at once (fit_file's host-memory
// promise is ~4 B/kept word; keeping all three would triple the peak on
// web-scale corpora). A second call returns -1.
int64_t corpus_encode(void* h, int64_t min_count, int64_t max_sentence_length,
                      int64_t* n_sentences_out) {
    auto* c = static_cast<Corpus*>(h);
    if (max_sentence_length <= 0) return -1;
    if (c->prov_consumed) return -1;
    ensure_sorted(c, min_count);
    // remap[gid] -> frequency rank, or -1 (dropped by min_count).
    std::vector<int32_t> remap(c->words.size(), -1);
    for (size_t i = 0; i < c->sorted_gids.size(); ++i)
        remap[static_cast<size_t>(c->sorted_gids[i])] =
            static_cast<int32_t>(i);
    c->enc_ids.clear();
    c->enc_lens.clear();
    c->enc_ids.reserve(c->prov.size());
    int64_t pos = 0;
    for (int64_t raw_len : c->prov_lens) {
        int64_t kept = 0;
        for (int64_t j = 0; j < raw_len; ++j) {
            int32_t r = remap[static_cast<size_t>(c->prov[pos + j])];
            if (r >= 0) {
                c->enc_ids.push_back(r);
                ++kept;
            }
        }
        pos += raw_len;
        while (kept > 0) {
            int64_t take = std::min(kept, max_sentence_length);
            c->enc_lens.push_back(take);
            kept -= take;
        }
    }
    c->prov_consumed = true;
    std::vector<int32_t>().swap(c->prov);
    std::vector<int64_t>().swap(c->prov_lens);
    if (n_sentences_out)
        *n_sentences_out = static_cast<int64_t>(c->enc_lens.size());
    return static_cast<int64_t>(c->enc_ids.size());
}

// Copies the corpus_encode results into caller-allocated `ids`
// (int32[n_ids]) and sentence offsets `soffs` (int64[n_sentences+1]),
// then frees the internal buffers (one-shot, like corpus_encode): after
// this call the caller's numpy arrays are the only copy.
int corpus_encode_fill(void* h, int32_t* ids, int64_t* soffs) {
    auto* c = static_cast<Corpus*>(h);
    if (!c->enc_ids.empty())
        std::memcpy(ids, c->enc_ids.data(),
                    c->enc_ids.size() * sizeof(int32_t));
    soffs[0] = 0;
    int64_t pos = 0;
    for (size_t i = 0; i < c->enc_lens.size(); ++i) {
        pos += c->enc_lens[i];
        soffs[i + 1] = pos;
    }
    std::vector<int32_t>().swap(c->enc_ids);
    std::vector<int64_t>().swap(c->enc_lens);
    return 0;
}

void corpus_free(void* h) { delete static_cast<Corpus*>(h); }

}  // extern "C"
