// host_ops: native host-side data-path kernels for glint_word2vec_tpu.
//
// The reference keeps its host data path on the JVM (Spark RDD passes); its
// only native touchpoints are netlib BLAS and Aeron shared memory (SURVEY.md
// §2.1 native-code census). In the TPU build the host data path must feed a
// chip at millions of words/sec, so the two measured hot spots live here:
//
//   1. alias_build    — O(V) Walker alias-table construction (the Python
//                       two-pointer loop takes minutes at 10M vocab).
//   2. window_batch   — per-epoch subsample + shrunk-window context/mask
//                       generation. Measured on the build host
//                       (scripts/host_path_bench.py -> HOSTPATH.json,
//                       20M-word Zipf corpus, 1M vocab, B=8192): 10.4M
//                       center positions/s (no subsample), 15.6M/s at
//                       subsample 1e-4; the Python/NumPy fallback pass
//                       measures 0.99M/s on the same corpus.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
// All buffers are caller-allocated NumPy arrays; nothing here allocates
// Python objects or touches the GIL, so callers may release it.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Walker/Vose alias table over `weights[0..n)`. Outputs:
//   prob[i]  in [0,1]  — acceptance probability for column i
//   alias[i] in [0,n)  — fallback index for column i
// Matches the Python reference implementation in corpus/alias.py (tested
// for distribution equality). Returns 0 on success, nonzero on bad input.
int alias_build(const double* weights, int64_t n, float* prob, int32_t* alias) {
    if (n <= 0) return 1;
    double total = 0.0;
    for (int64_t i = 0; i < n; ++i) {
        double w = weights[i];
        if (!(w >= 0.0) || w != w) return 2;  // negative or NaN
        total += w;
    }
    if (!(total > 0.0)) return 3;

    std::vector<double> scaled(n);
    const double k = static_cast<double>(n) / total;
    for (int64_t i = 0; i < n; ++i) scaled[i] = weights[i] * k;

    // Two-pointer partition: indices of small (<1) and large (>=1) columns.
    std::vector<int64_t> small, large;
    small.reserve(n);
    large.reserve(n);
    for (int64_t i = 0; i < n; ++i) {
        prob[i] = 1.0f;
        alias[i] = static_cast<int32_t>(i);
        (scaled[i] < 1.0 ? small : large).push_back(i);
    }
    while (!small.empty() && !large.empty()) {
        int64_t s = small.back();
        small.pop_back();
        int64_t l = large.back();
        large.pop_back();
        prob[s] = static_cast<float>(scaled[s]);
        alias[s] = static_cast<int32_t>(l);
        scaled[l] = (scaled[l] + scaled[s]) - 1.0;
        if (scaled[l] < 1.0) small.push_back(l); else large.push_back(l);
    }
    return 0;
}

// xorshift128+ PRNG — fast, well-distributed, deterministic per seed.
struct Rng {
    uint64_t s0, s1;
    explicit Rng(uint64_t seed) {
        // splitmix64 seeding
        auto next = [&seed]() {
            seed += 0x9E3779B97f4A7C15ULL;
            uint64_t z = seed;
            z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
            z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
            return z ^ (z >> 31);
        };
        s0 = next();
        s1 = next();
    }
    inline uint64_t next_u64() {
        uint64_t x = s0;
        const uint64_t y = s1;
        s0 = y;
        x ^= x << 23;
        s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
        return s1 + y;
    }
    // uniform double in [0, 1)
    inline double next_double() {
        return (next_u64() >> 11) * (1.0 / 9007199254740992.0);
    }
    // uniform int in [0, m)
    inline int64_t next_below(int64_t m) {
        return static_cast<int64_t>(next_u64() % static_cast<uint64_t>(m));
    }
};

// One epoch pass over a flattened corpus: frequency subsampling + shrunk-
// window context generation, emitting fixed-width rows.
//
// Inputs:
//   ids        — concatenated sentence word-indices, int32[total_len]
//   offsets    — sentence boundaries, int64[n_sentences+1]
//   keep_prob  — per-word keep probability, float32[vocab] (all-1 disables)
//   window     — reference windowSize; per position draw b in [0, window)
//                and take offsets [-b, b-1] \ {0} (mllib:384-388)
//   seed       — epoch seed (caller mixes epoch index)
// Outputs (caller-allocated, capacity rows >= total_len):
//   centers    — int32[capacity]
//   contexts   — int32[capacity * ctx_width]   (ctx_width = 2*window - 3,
//                matching corpus.batching.context_width; zero-padded)
//   mask       — float32[capacity * ctx_width]
// Returns the number of rows written (= number of kept word positions), or
// -1 if capacity was insufficient.
int64_t window_batch_epoch(
    const int32_t* ids, const int64_t* offsets, int64_t n_sentences,
    const float* keep_prob, int32_t window, uint64_t seed,
    int32_t* centers, int32_t* contexts, float* mask,
    int64_t capacity, int64_t* words_done_out) {
    const int64_t W = window;
    const int64_t C = (2 * W - 3) > 1 ? (2 * W - 3) : 1;
    Rng rng(seed);
    int64_t row = 0;
    int64_t words_done = 0;
    std::vector<int32_t> kept;
    for (int64_t s = 0; s < n_sentences; ++s) {
        const int64_t beg = offsets[s], end = offsets[s + 1];
        words_done += end - beg;
        kept.clear();
        for (int64_t i = beg; i < end; ++i) {
            const int32_t w = ids[i];
            const float kp = keep_prob[w];
            if (kp >= 1.0f || rng.next_double() <= kp) kept.push_back(w);
        }
        const int64_t L = static_cast<int64_t>(kept.size());
        if (row + L > capacity) return -1;
        for (int64_t i = 0; i < L; ++i) {
            const int64_t b = (W > 0) ? rng.next_below(W) : 0;  // [0, W)
            centers[row] = kept[i];
            int32_t* ctx = contexts + row * C;
            float* m = mask + row * C;
            std::memset(ctx, 0, sizeof(int32_t) * C);
            std::memset(m, 0, sizeof(float) * C);
            // context positions [max(0,i-b), min(i+b,L)) excluding i;
            // lane layout matches corpus.batching.window_offsets:
            // lanes [0, W-1) hold offsets -(W-1)..-1, lanes [W-1, C) hold
            // offsets 1..W-2.
            const int64_t lo = (i - b) > 0 ? (i - b) : 0;
            const int64_t hi = (i + b) < L ? (i + b) : L;
            for (int64_t j = lo; j < hi; ++j) {
                if (j == i) continue;
                const int64_t off = j - i;  // in [-(W-1), W-2], != 0
                const int64_t lane = off < 0 ? off + (W - 1) : (W - 1) + off - 1;
                ctx[lane] = kept[static_cast<size_t>(j)];
                m[lane] = 1.0f;
            }
            ++row;
        }
    }
    if (words_done_out) *words_done_out = words_done;
    return row;
}

}  // extern "C"
