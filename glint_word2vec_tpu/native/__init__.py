"""Native host-ops library: build-on-first-use C++ kernels via ctypes.

See host_ops.cpp for what lives here and why. The library is compiled once
into ``_host_ops.so`` next to the source (g++ -O3) and loaded with ctypes;
every entry point has a pure-Python fallback, so the package works without a
compiler (``GLINT_W2V_NO_NATIVE=1`` forces the fallbacks, used in tests to
cover both paths).
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "host_ops.cpp")
_SO = os.path.join(_HERE, "_host_ops.so")
_STAMP = _SO + ".sha256"  # source hash the .so was built from
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _src_hash() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _is_fresh() -> bool:
    """A .so is usable only if built from the current source ON this machine
    (-march=native output from another host can SIGILL); the build stamp
    records the source hash, and a missing stamp forces a rebuild."""
    if not os.path.exists(_SO) or not os.path.exists(_STAMP):
        return False
    try:
        with open(_STAMP) as f:
            return f.read().strip() == _src_hash()
    except OSError:
        return False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-pthread", _SRC, "-o", _SO,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        # graftlint: ignore[atomic-persist] best-effort build stamp: a torn stamp only fails the hash check and forces one rebuild
        with open(_STAMP, "w") as f:
            f.write(_src_hash())
        return True
    except Exception as e:  # compiler missing, read-only fs, ...
        logger.warning("native host_ops build failed (%s); using Python fallbacks", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("GLINT_W2V_NO_NATIVE"):
            _load_failed = True
            return None
        if not _is_fresh():
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            logger.warning("native host_ops load failed (%s)", e)
            _load_failed = True
            return None
        lib.alias_build.restype = ctypes.c_int
        lib.alias_build.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.window_batch_epoch.restype = ctypes.c_int64
        lib.window_batch_epoch.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float), ctypes.c_int32,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ]
        lib.corpus_open.restype = ctypes.c_void_p
        lib.corpus_open.argtypes = [ctypes.c_char_p, ctypes.c_int32]
        lib.corpus_vocab_size.restype = ctypes.c_int64
        lib.corpus_vocab_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.corpus_vocab_chars.restype = ctypes.c_int64
        lib.corpus_vocab_chars.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.corpus_vocab_fill.restype = ctypes.c_int
        lib.corpus_vocab_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.corpus_encode.restype = ctypes.c_int64
        lib.corpus_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.corpus_encode_fill.restype = ctypes.c_int
        lib.corpus_encode_fill.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.corpus_free.restype = None
        lib.corpus_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _resolve_threads(threads: Optional[int]) -> int:
    """Thread count for the native parallel passes: an explicit argument
    wins; otherwise GLINT_NATIVE_THREADS (non-numeric/empty tolerated);
    0 = one per hardware core (resolved in C++)."""
    if threads is not None:
        return int(threads)
    try:
        return int(os.environ.get("GLINT_NATIVE_THREADS", "0"))
    except ValueError:
        return 0


def alias_build_native(weights: np.ndarray) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native alias-table construction; None if the library is unavailable.
    Raises ValueError for invalid weights (mirroring the Python builder)."""
    lib = get_lib()
    if lib is None:
        return None
    w = np.ascontiguousarray(weights, dtype=np.float64)
    n = w.size
    prob = np.empty(n, dtype=np.float32)
    alias = np.empty(n, dtype=np.int32)
    rc = lib.alias_build(
        _ptr(w, ctypes.c_double), n, _ptr(prob, ctypes.c_float),
        _ptr(alias, ctypes.c_int32),
    )
    if rc == 1:
        raise ValueError("weights must be a nonempty 1-D array")
    if rc == 2:
        raise ValueError("weights must be finite and nonnegative")
    if rc == 3:
        raise ValueError("weights must sum to > 0")
    return prob, alias


def window_batch_epoch_native(
    ids: np.ndarray,
    offsets: np.ndarray,
    keep_prob: np.ndarray,
    window: int,
    seed: int,
    threads: Optional[int] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, int]]:
    """Run a full subsample+window epoch pass natively, parallel across
    sentence chunks. The output is byte-identical for every thread count
    (deterministic per-sentence PRNG seeds + a two-phase count/fill).

    ``threads``: worker count; None reads GLINT_NATIVE_THREADS, else 0 =
    one per hardware core. Returns (centers, contexts, mask, words_done)
    with exactly the kept rows, or None if the library is unavailable.
    """
    lib = get_lib()
    if lib is None:
        return None
    threads = _resolve_threads(threads)
    C = max(1, 2 * int(window) - 3)
    ids_c = np.ascontiguousarray(ids, dtype=np.int32)
    off_c = np.ascontiguousarray(offsets, dtype=np.int64)
    kp_c = np.ascontiguousarray(keep_prob, dtype=np.float32)
    cap = int(ids_c.size)
    centers = np.empty(cap, dtype=np.int32)
    contexts = np.empty((cap, C), dtype=np.int32)
    mask = np.empty((cap, C), dtype=np.float32)
    words_done = ctypes.c_int64(0)
    rows = lib.window_batch_epoch(
        _ptr(ids_c, ctypes.c_int32), _ptr(off_c, ctypes.c_int64),
        off_c.size - 1, _ptr(kp_c, ctypes.c_float), int(window),
        ctypes.c_uint64(seed & (2**64 - 1)), _ptr(centers, ctypes.c_int32),
        _ptr(contexts, ctypes.c_int32), _ptr(mask, ctypes.c_float),
        cap, ctypes.byref(words_done), int(threads),
    )
    if rows < 0:  # capacity == total ids, so this cannot happen
        raise RuntimeError("window_batch_epoch capacity overflow")
    return (
        centers[:rows], contexts[:rows], mask[:rows], int(words_done.value)
    )


def corpus_scan_native(
    path: str,
    min_count: int,
    max_sentence_length: int,
    lowercase: bool = False,
    threads: Optional[int] = None,
) -> Optional[Tuple[list, np.ndarray, np.ndarray, np.ndarray]]:
    """Native fit_file ingestion: both corpus passes (vocab count + flat
    encode) in C++, one file handle each. The counting pass runs
    thread-parallel over mmap'd chunks for large files, with output
    identical to the sequential pass for every thread count; ``threads``
    None reads GLINT_NATIVE_THREADS (0 = one per hardware core).

    Returns ``(words, counts int64[n], ids int32[total], offsets
    int64[n_sentences+1])`` — the inputs ``Vocabulary`` + the flat corpus
    representation are built from — or None when the caller should use the
    Python path instead: native library unavailable, file unreadable,
    invalid UTF-8 anywhere in the corpus (Python's errors='replace' decode
    merges tokens differing only in invalid bytes — byte-level counting
    cannot reproduce that), or ``lowercase`` requested (``str.lower`` is
    Unicode-aware). An empty vocab returns empty arrays; the caller
    decides whether that is an error (``Vocabulary.from_sorted`` raises).

    Token/tie-break semantics match corpus/vocab.py exactly for valid
    UTF-8 corpora: full str.split() whitespace set, universal-newline
    sentence boundaries, count desc, first-seen order on ties, OOV
    dropped, per-line chunking at ``max_sentence_length`` (equality is
    unit-tested against the Python passes).
    """
    if lowercase:
        return None
    lib = get_lib()
    if lib is None:
        return None
    h = lib.corpus_open(os.fsencode(path), _resolve_threads(threads))
    if not h:
        return None
    try:
        n = int(lib.corpus_vocab_size(h, min_count))
        if n <= 0:
            return (
                [], np.zeros(0, np.int64), np.zeros(0, np.int32),
                np.zeros(1, np.int64),
            )
        nchars = int(lib.corpus_vocab_chars(h, min_count))
        chars = ctypes.create_string_buffer(max(nchars, 1))
        offs = np.empty(n + 1, dtype=np.int64)
        counts = np.empty(n, dtype=np.int64)
        lib.corpus_vocab_fill(
            h, min_count, chars, _ptr(offs, ctypes.c_int64),
            _ptr(counts, ctypes.c_int64),
        )
        raw = chars.raw[:nchars]
        bounds = offs.tolist()
        words = [
            raw[bounds[i]:bounds[i + 1]].decode("utf-8", errors="replace")
            for i in range(n)
        ]
        n_sent = ctypes.c_int64(0)
        n_ids = int(
            lib.corpus_encode(
                h, min_count, max_sentence_length, ctypes.byref(n_sent)
            )
        )
        if n_ids < 0:
            return None
        ids = np.empty(max(n_ids, 1), dtype=np.int32)[:n_ids]
        soffs = np.empty(int(n_sent.value) + 1, dtype=np.int64)
        lib.corpus_encode_fill(
            h, _ptr(ids, ctypes.c_int32), _ptr(soffs, ctypes.c_int64)
        )
        return words, counts, ids, soffs
    finally:
        lib.corpus_free(h)
