"""Resumable bulk embedding: stream a sentence file through the sharded
tables at full device utilization and write vector shards.

The reference system's real product is not a synonym endpoint — it is
DataFrame ``transform``: embed an entire corpus offline through the
server-side ``pullAverage`` op (PAPER.md layer L5). This module is that
pipeline rebuilt on the repo's serving discipline:

- a producer thread reads, tokenizes, encodes and packs sentences into
  dense fixed-shape ``(rows, len)`` pow2-bucketed batches
  (:func:`corpus.batching.pack_query_block`), double-buffered through
  :func:`utils.prefetch.prefetch` so the device never waits on the host
  — the PR 5 stall-free discipline applied to inference;
- the jitted pull-average program family is warmed before the stream
  starts (``Model.bulk_warmup``) and steady state is asserted
  compile-free via ``engine.query_compiles``, exactly like serving;
- output is fixed-size ``.npy`` vector shards with per-shard sidecar
  manifests and an atomically committed progress record — the PR 7/15
  checkpoint integrity layer reused verbatim — so a SIGKILL at any
  point resumes bitwise from the last committed shard;
- ranks parallelize over contiguous input spans
  (:func:`parallel.distributed.shard_span`) under the PR 7 supervisor,
  each writing its own shard directory.

Output contract: output row ``i`` of the concatenated shards is the
embedding of input line ``start + i`` — blank and all-OOV lines become
zero vectors (the reference's empty-sentence average), they are never
dropped. This module is deliberately jax-free: every device dispatch
goes through the model's ``transform_packed`` hook, so the word-level
and subword-compose families share one pipeline.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Iterator, List, Optional, Sequence

import numpy as np

from glint_word2vec_tpu.corpus.batching import pack_query_block
from glint_word2vec_tpu.utils import (
    atomic_write_json,
    atomic_write_npy,
)
from glint_word2vec_tpu.utils import faults
from glint_word2vec_tpu.utils.integrity import (
    CheckpointCorruptError,
    build_shard_manifest,
    verify_shard,
    write_shard_manifest,
)
from glint_word2vec_tpu.utils.prefetch import prefetch

logger = logging.getLogger(__name__)

PROGRESS_NAME = "progress.json"
SHARD_PATTERN = "shard-{:06d}.npy"


def count_lines(path: str) -> int:
    """Line count of a text file (a trailing line without a newline
    counts). One buffered binary pass — the bulk pipeline's sizing scan,
    run before any device work."""
    n = 0
    last = b"\n"
    with open(path, "rb") as f:
        while True:
            buf = f.read(1 << 20)
            if not buf:
                break
            n += buf.count(b"\n")
            last = buf[-1:]
    if last != b"\n":
        n += 1
    return n


def iter_sentence_lines(
    path: str,
    *,
    start: int = 0,
    end: Optional[int] = None,
    lowercase: bool = False,
) -> Iterator[List[str]]:
    """Tokenized sentences from line span ``[start, end)``. Unlike
    :func:`corpus.vocab.iter_text_file` this PRESERVES blank lines (as
    empty token lists -> zero vectors downstream): the bulk transform's
    contract is one output row per input line, so row ``i`` of the
    vector shards always aligns with line ``start + i`` of the input."""
    with open(path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f):
            if i < start:
                continue
            if end is not None and i >= end:
                break
            yield (line.lower() if lowercase else line).split()


def _ckpt_fsync() -> bool:
    return os.environ.get("GLINT_CKPT_NO_FSYNC", "0") != "1"


class ShardWriter:
    """Fixed-size ``.npy`` vector shards + integrity sidecars + an
    atomically committed progress record.

    Each full buffer commits as ``shard-NNNNNN.npy`` (atomic temp +
    ``os.replace``) with a ``<shard>.manifest.json`` sidecar
    (:func:`utils.integrity.write_shard_manifest` — the ISSUE 15
    checkpoint contract verbatim), then the progress record is replaced.
    The resume scan — not the progress record — is the source of truth:
    a kill between a shard commit and the progress write leaves a valid
    shard the record does not mention, and recomputing it would only
    rewrite identical bytes. The record cross-checks the run geometry
    (input name, span, shard size, dim): a mismatch raises instead of
    silently mixing incompatible shards."""

    def __init__(
        self,
        out_dir: str,
        *,
        shard_size: int,
        dim: int,
        meta: dict,
        fsync: Optional[bool] = None,
    ):
        if shard_size < 1:
            raise ValueError("shard_size must be >= 1")
        self.out_dir = out_dir
        self.shard_size = int(shard_size)
        self.dim = int(dim)
        self.meta = dict(meta)
        self.fsync = _ckpt_fsync() if fsync is None else bool(fsync)
        os.makedirs(out_dir, exist_ok=True)
        self._buf = np.zeros((self.shard_size, self.dim), np.float32)
        self._fill = 0
        self.shard_index = 0
        self.sentences_done = 0
        self.committed = 0
        self.skipped = 0
        self.shard_commit_seconds = 0.0

    # -- resume ---------------------------------------------------------

    def _progress_path(self) -> str:
        return os.path.join(self.out_dir, PROGRESS_NAME)

    def resume_scan(self, total_sentences: int, *, deep: bool = True) -> int:
        """Longest valid committed-shard prefix; returns the sentence
        count it covers (0 = fresh start). Every prefix shard is
        verified against its sidecar manifest (``deep=True`` re-hashes
        payloads — bit rot or a torn write ends the prefix, and the
        deterministic pipeline simply recomputes identical bytes from
        there). A partial (short) shard only counts when it is the
        final shard of a COMPLETED span; anywhere else it marks the
        kill point and is recomputed."""
        prog = None
        if os.path.exists(self._progress_path()):
            try:
                with open(self._progress_path()) as f:
                    prog = json.load(f)
            except (ValueError, OSError) as e:
                logger.warning(
                    "unreadable %s (%s); falling back to the shard scan",
                    self._progress_path(), e,
                )
        if prog is not None:
            for key, want in self.meta.items():
                got = prog.get(key)
                if got != want:
                    raise CheckpointCorruptError(
                        f"{self.out_dir}: progress record {key}={got!r} "
                        f"does not match this run's {key}={want!r} — "
                        "refusing to mix shards from a different "
                        "transform (point --out at a fresh directory)"
                    )
        done = 0
        k = 0
        while done < total_sentences:
            fname = SHARD_PATTERN.format(k)
            path = os.path.join(self.out_dir, fname)
            if not os.path.exists(path):
                break
            try:
                verify_shard(self.out_dir, fname, deep=deep)
            except CheckpointCorruptError as e:
                logger.warning(
                    "resume scan stops at %s: %s (recomputing from "
                    "sentence %d)", fname, e, done,
                )
                break
            rows = int(np.load(path, mmap_mode="r").shape[0])
            if rows < self.shard_size and done + rows < total_sentences:
                logger.warning(
                    "resume scan stops at short shard %s (%d rows "
                    "mid-span): recomputing from sentence %d",
                    fname, rows, done,
                )
                break
            done += rows
            k += 1
        self.shard_index = k
        self.skipped = k
        self.sentences_done = done
        return done

    # -- writing --------------------------------------------------------

    def append(self, vecs: np.ndarray) -> None:
        pos = 0
        while pos < len(vecs):
            take = min(self.shard_size - self._fill, len(vecs) - pos)
            self._buf[self._fill : self._fill + take] = vecs[pos : pos + take]
            self._fill += take
            pos += take
            if self._fill == self.shard_size:
                self._commit()

    def finish(self) -> None:
        if self._fill:
            self._commit()
        self._write_progress(final=True)

    def _commit(self) -> None:
        t0 = time.perf_counter()
        fname = SHARD_PATTERN.format(self.shard_index)
        path = os.path.join(self.out_dir, fname)
        atomic_write_npy(path, self._buf[: self._fill])
        write_shard_manifest(
            self.out_dir, fname, build_shard_manifest(self.out_dir, fname),
            fsync=self.fsync,
        )
        # Fault point AFTER the shard + sidecar are durable but BEFORE
        # the progress record: a kill here leaves a committed shard the
        # record does not mention — exactly the window the resume scan
        # (not the record) is the source of truth for.
        faults.fire("transform.shard_commit")
        self.sentences_done += self._fill
        self.shard_index += 1
        self.committed += 1
        self._fill = 0
        self.shard_commit_seconds += time.perf_counter() - t0
        self._write_progress()

    def _write_progress(self, final: bool = False) -> None:
        atomic_write_json(
            self._progress_path(),
            {
                **self.meta,
                "shards": self.shard_index,
                "sentences_done": self.sentences_done,
                "complete": bool(final),
            },
        )


def _packed_batches(
    model,
    input_path: str,
    *,
    rows: int,
    max_len: int,
    start: int,
    end: int,
    lowercase: bool,
    stats: dict,
):
    """Producer generator: encode + pack ``rows``-sentence blocks into
    pow2 ``(idx, mask, n)`` batches. Runs on the prefetch thread; the
    ``stats`` dict is shared with the consumer (int/float slot updates,
    safe under the GIL; the consumer only reads them for telemetry
    until the stream is drained)."""
    vocab = model.vocab
    buf: List[np.ndarray] = []

    def _pack(block: Sequence[np.ndarray]):
        t0 = time.perf_counter()
        idx, mask, n = pack_query_block(block, rows=rows)
        faults.fire("transform.producer")
        stats["producer_seconds"] += time.perf_counter() - t0
        stats["batches"] += 1
        if idx is not None:
            stats["fill_tokens"] += int(sum(len(x) for x in block))
            stats["fill_capacity"] += int(idx.size)
        return idx, mask, n

    for toks in iter_sentence_lines(
        input_path, start=start, end=end, lowercase=lowercase
    ):
        enc = vocab.encode(toks)
        if len(enc) > max_len:
            enc = enc[:max_len]
            stats["truncated_sentences"] += 1
        buf.append(enc)
        if len(buf) == rows:
            yield _pack(buf)
            buf = []
    if buf:
        yield _pack(buf)


def transform_file(
    model,
    input_path: str,
    out_dir: str,
    *,
    rows: int = 1024,
    max_len: int = 256,
    shard_size: int = 8192,
    start: int = 0,
    end: Optional[int] = None,
    lowercase: bool = False,
    prefetch_depth: int = 2,
    deep_verify: bool = True,
    warmup: bool = True,
    obs_run=None,
) -> dict:
    """Embed line span ``[start, end)`` of ``input_path`` into vector
    shards under ``out_dir``; returns the run's stats document.

    ``shard_size`` is rounded up to a multiple of ``rows`` so shard
    boundaries always fall on batch boundaries — resumes then re-form
    byte-identical batches without leaning on the padding-exactness
    argument alone. ``end=None`` means end-of-file. ``obs_run`` (an
    ``ObsRun`` or the null run) receives ``update_transform`` gauge
    updates on the batch cadence."""
    if rows < 1:
        raise ValueError("rows must be >= 1")
    if max_len < 1:
        raise ValueError("max_len must be >= 1")
    if start < 0:
        raise ValueError("start must be >= 0")
    shard_size = max(rows, (int(shard_size) + rows - 1) // rows * rows)
    if end is None:
        end = count_lines(input_path)
    if end < start:
        raise ValueError(f"span [{start}, {end}) is empty or inverted")
    total = end - start
    dim = int(model.vector_size)

    meta = {
        "version": 1,
        "input": os.path.basename(input_path),
        "span": [int(start), int(end)],
        "rows": int(rows),
        "max_len": int(max_len),
        "shard_size": int(shard_size),
        "dim": dim,
        "lowercase": bool(lowercase),
    }
    writer = ShardWriter(
        out_dir, shard_size=shard_size, dim=dim, meta=meta
    )
    resumed = writer.resume_scan(total, deep=deep_verify)
    if resumed:
        logger.info(
            "resuming from %d committed shard(s): %d/%d sentences "
            "already on disk", writer.skipped, resumed, total,
        )

    warmup_compiles = 0
    if warmup:
        t0 = time.perf_counter()
        warmup_compiles = model.bulk_warmup(rows, max_len)
        logger.info(
            "bulk warmup: %d shape(s) compiled in %.1fs",
            warmup_compiles, time.perf_counter() - t0,
        )
    compiles_after_warmup = model.engine.query_compiles

    stats = {
        "producer_seconds": 0.0,
        "truncated_sentences": 0,
        "batches": 0,
        "fill_tokens": 0,
        "fill_capacity": 0,
    }
    producer_wait = 0.0
    dispatch_seconds = 0.0
    done = resumed
    t_start = time.perf_counter()
    it = prefetch(
        _packed_batches(
            model, input_path, rows=rows, max_len=max_len,
            start=start + resumed, end=end, lowercase=lowercase,
            stats=stats,
        ),
        depth=prefetch_depth,
    )

    def _fill() -> Optional[float]:
        cap = stats["fill_capacity"]
        return stats["fill_tokens"] / cap if cap else None

    def _obs(final: bool = False) -> None:
        if obs_run is None:
            return
        elapsed = max(time.perf_counter() - t_start, 1e-9)
        obs_run.update_transform(
            sentences_done=done,
            input_sentences=total,
            sentences_per_sec=(done - resumed) / elapsed,
            shards_committed=writer.committed,
            shards_skipped=writer.skipped,
            bucket_fill=_fill(),
            producer_wait_seconds=producer_wait,
            dispatch_seconds=dispatch_seconds,
            post_warmup_compiles=(
                model.engine.query_compiles - compiles_after_warmup
            ),
        )

    _obs()
    while True:
        t0 = time.perf_counter()
        batch = next(it, None)
        producer_wait += time.perf_counter() - t0
        if batch is None:
            break
        idx, mask, n = batch
        t0 = time.perf_counter()
        if idx is None:
            vecs = np.zeros((n, dim), np.float32)
        else:
            vecs = model.transform_packed(idx, mask)[:n]
        dispatch_seconds += time.perf_counter() - t0
        writer.append(vecs)
        done += n
        _obs()
    writer.finish()
    _obs(final=True)

    wall = time.perf_counter() - t_start
    post_warmup = model.engine.query_compiles - compiles_after_warmup
    if post_warmup:
        logger.warning(
            "%d post-warmup query compile(s) hit the steady-state "
            "stream — the warmed family missed a shape", post_warmup,
        )
    fill = _fill()
    return {
        "input": input_path,
        "out_dir": out_dir,
        "span": [int(start), int(end)],
        "sentences": total,
        "sentences_done": done,
        "resumed_sentences": resumed,
        "shards_committed": writer.committed,
        "shards_skipped": writer.skipped,
        "shard_commit_seconds": round(writer.shard_commit_seconds, 4),
        "wall_seconds": round(wall, 4),
        "sentences_per_sec": round((done - resumed) / max(wall, 1e-9), 1),
        "bucket_fill": round(fill, 4) if fill is not None else None,
        "producer_wait_seconds": round(producer_wait, 4),
        "producer_seconds": round(stats["producer_seconds"], 4),
        "dispatch_seconds": round(dispatch_seconds, 4),
        "host_stall_frac": round(producer_wait / max(wall, 1e-9), 4),
        "truncated_sentences": stats["truncated_sentences"],
        "batches": stats["batches"],
        "warmup_compiles": warmup_compiles,
        "post_warmup_compiles": post_warmup,
        "rows": int(rows),
        "max_len": int(max_len),
        "shard_size": int(shard_size),
        "dim": dim,
    }


def load_transform_output(out_dir: str) -> np.ndarray:
    """Concatenate a transform run's committed shards, in order, into
    one ``(sentences, d)`` array — the verification/consumption helper
    (bench drills sha-compare this against an uninterrupted run)."""
    parts = []
    k = 0
    while True:
        path = os.path.join(out_dir, SHARD_PATTERN.format(k))
        if not os.path.exists(path):
            break
        parts.append(np.load(path))
        k += 1
    if not parts:
        return np.zeros((0, 0), np.float32)
    return np.concatenate(parts, axis=0)


# ----------------------------------------------------------------------
# ANN-powered batch jobs (ISSUE 12 index, batch amortization regime)
# ----------------------------------------------------------------------


def synonyms_dump(
    model,
    out_path: Optional[str],
    *,
    num: int = 10,
    block: int = 1024,
    approximate: bool = False,
    graph_prefix: Optional[str] = None,
    start: int = 0,
    end: Optional[int] = None,
) -> dict:
    """All-vocab top-``num`` neighbor dump: JSONL at ``out_path`` (one
    ``{"word", "synonyms": [[word, sim], ...]}`` object per vocab word,
    self-match excluded) and/or a k-NN graph (``graph_prefix`` writes
    ``<prefix>.ids.npy`` int32 ``(V, num)`` neighbor ids, ``-1`` padded,
    ``<prefix>.sims.npy`` float32 sims, and a ``<prefix>.json`` meta
    document). One pass over the table either way: vocab vectors stream
    ``block`` rows at a time through the query engine and
    ``find_synonyms_batch`` — whole-table batch top-k being the ANN
    index's best amortization regime (build cost over V queries).
    ``approximate=True`` rides an adopted index, with
    ``find_synonyms_batch``'s capacity escape to exact. Outputs are
    written temp + ``os.replace`` (atomic, resume-by-rerun)."""
    if num < 1:
        raise ValueError("num must be >= 1")
    if block < 1:
        raise ValueError("block must be >= 1")
    vocab_size = model.vocab.size
    words = model.vocab.words
    end = vocab_size if end is None else min(int(end), vocab_size)
    start = max(0, int(start))
    if end < start:
        raise ValueError(f"vocab span [{start}, {end}) is inverted")
    qeng = model._query_engine()
    n_words = end - start
    want_graph = graph_prefix is not None
    ids_out = (
        np.full((n_words, num), -1, np.int32) if want_graph else None
    )
    sims_out = (
        np.zeros((n_words, num), np.float32) if want_graph else None
    )

    t0 = time.perf_counter()
    f = tmp = None
    if out_path is not None:
        tmp = f"{out_path}.tmp.{os.getpid()}"
        f = open(tmp, "w", encoding="utf-8")
    try:
        for s in range(start, end, block):
            ids = np.arange(s, min(s + block, end), dtype=np.int32)
            vecs = np.asarray(qeng.pull(ids))
            # num + 1 so the word itself can be dropped, the
            # find_synonyms contract applied vocab-wide.
            hits = model.find_synonyms_batch(
                vecs, num + 1, approximate=approximate
            )
            for wid, row in zip(ids, hits):
                word = words[int(wid)]
                kept = [(w, sim) for w, sim in row if w != word][:num]
                if f is not None:
                    f.write(json.dumps({
                        "word": word,
                        "synonyms": [
                            [w, round(float(sim), 6)] for w, sim in kept
                        ],
                    }) + "\n")
                if want_graph:
                    r = int(wid) - start
                    widx = model.vocab.word_index
                    for j, (w, sim) in enumerate(kept):
                        ids_out[r, j] = widx[w]
                        sims_out[r, j] = sim
        if f is not None:
            f.close()
            f = None
            os.replace(tmp, out_path)
            tmp = None
    finally:
        if f is not None:
            f.close()
        if tmp is not None and os.path.exists(tmp):
            os.unlink(tmp)

    seconds = time.perf_counter() - t0
    out = {
        "words": n_words,
        "num": int(num),
        "block": int(block),
        "approximate": bool(approximate),
        "seconds": round(seconds, 4),
        "words_per_sec": round(n_words / max(seconds, 1e-9), 1),
    }
    if out_path is not None:
        out["out"] = out_path
    if want_graph:
        atomic_write_npy(f"{graph_prefix}.ids.npy", ids_out)
        atomic_write_npy(f"{graph_prefix}.sims.npy", sims_out)
        atomic_write_json(
            f"{graph_prefix}.json",
            {**out, "ids": f"{graph_prefix}.ids.npy",
             "sims": f"{graph_prefix}.sims.npy",
             "pad_id": -1},
        )
        out["graph_prefix"] = graph_prefix
    return out
