"""Pod-scale batch transform (ISSUE 17): resumable bulk embedding.

The throughput twin of the latency-optimized serving stack — no request
jitter, no coalescing heuristics, just the "fixed traced shapes, compile
once, stream forever" discipline applied to offline inference. See
:mod:`glint_word2vec_tpu.batch.transform`.
"""

from glint_word2vec_tpu.batch.transform import (  # noqa: F401
    ShardWriter,
    count_lines,
    iter_sentence_lines,
    load_transform_output,
    synonyms_dump,
    transform_file,
)
