"""Device-side ops: the SGNS step math, on-device negative sampling, top-k."""
