"""Pallas TPU kernels for the sparse row traffic of the SGNS step.

The hot sparse ops in the engine (parallel/engine.py) are row gathers
(``_pull_rows``) and rank-1 row scatter-adds (``_scatter_rows``) into the
(V, d) embedding tables — the device-side restatement of what the reference
parameter servers do inside ``dotprod``/``adjust`` (mllib:421-425). XLA
lowers them to generic gather/scatter; these kernels instead move rows with
explicit per-row DMAs.

Design (round 2 — round 1 streamed one (1, d) block per grid step, a
sublane-1 block shape with an N-step scalar grid, flagged as probably slow):
both kernels now process ``block_rows`` rows per grid step with manual
HBM<->VMEM DMAs (``pltpu.make_async_copy``) issued from a scalar-prefetched
index vector, so up to ``block_rows`` row copies are in flight at once and
grid overhead is amortized ``block_rows``-fold. The table itself never
enters the automatic pipeline (``pl.ANY`` memory space): only the touched
rows move.

Correctness contract for the scatter: duplicate target rows must SUM their
updates (synchronous-batch semantics, SURVEY.md §7 hard part 1). Updates
are sorted by row id (duplicates become adjacent), each grid step
accumulates its block's runs of equal ids sequentially in VMEM, and one
read-modify-write DMA per run lands the total. TPU grid steps execute
sequentially on a core and every write DMA is waited before the step ends,
so a run spanning two blocks is just two ordered read-modify-writes of the
same row — still a sum.

These kernels are OPT-IN (engine flag / GLINT_W2V_PALLAS env var): XLA's
native lowering is the default until per-hardware measurement says
otherwise (scripts/pallas_bench.py is the measurement harness). On CPU they
run in interpret mode, which is how the unit tests exercise them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _pad_rows(n: int, block_rows: int) -> int:
    return -(-n // block_rows) * block_rows


# ----------------------------------------------------------------------
# Gather
# ----------------------------------------------------------------------


def _gather_kernel(block_rows, ids_ref, table_ref, out_ref, sems):
    base = pl.program_id(0) * block_rows

    def start(j, _):
        pltpu.make_async_copy(
            table_ref.at[ids_ref[base + j]], out_ref.at[j], sems.at[j]
        ).start()
        return 0

    lax.fori_loop(0, block_rows, start, 0)

    def wait(j, _):
        pltpu.make_async_copy(
            table_ref.at[ids_ref[base + j]], out_ref.at[j], sems.at[j]
        ).wait()
        return 0

    lax.fori_loop(0, block_rows, wait, 0)


@functools.partial(jax.jit, static_argnames=("interpret", "block_rows"))
def gather_rows(
    table: jax.Array,
    ids: jax.Array,
    *,
    interpret: bool = False,
    block_rows: int = 16,
):
    """``table[ids]`` as a Pallas row pipeline: ``block_rows`` per-row DMAs
    in flight per grid step, addresses from the prefetched ``ids``."""
    N = ids.shape[0]
    d = table.shape[1]
    Np = _pad_rows(N, block_rows)
    ids_p = jnp.pad(ids.astype(jnp.int32), (0, Np - N))  # pad rows read row 0
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Np // block_rows,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],  # table stays in HBM
        out_specs=pl.BlockSpec((block_rows, d), lambda i, ids: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((block_rows,))],
    )
    out = pl.pallas_call(
        functools.partial(_gather_kernel, block_rows),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Np, d), table.dtype),
        interpret=interpret,
    )(ids_p, table)
    return out[:N]


# ----------------------------------------------------------------------
# Scatter-add
# ----------------------------------------------------------------------


def _scatter_runs(
    block_rows, upd_fn, ids_ref, out_ref, tbl, wb, acc, rsems, wsems
):
    """The shared run-summing scatter pipeline. ``upd_fn(j, gj) -> row``
    produces update row j (block-local) / gj (global) in table dtype; the
    two kernels below differ ONLY in that producer.

    out_ref aliases the table's storage (input_output_aliases); all row
    traffic is explicit DMA against it. ids are sorted globally, so equal
    ids form runs that are contiguous within and across blocks.
    """
    base = pl.program_id(0) * block_rows

    # Read phase: fetch the current table row for every update row
    # (duplicates re-read the same row; only each run's first copy is used).
    def rstart(j, _):
        pltpu.make_async_copy(
            out_ref.at[ids_ref[base + j]], tbl.at[j], rsems.at[j]
        ).start()
        return 0

    lax.fori_loop(0, block_rows, rstart, 0)

    def rwait(j, _):
        pltpu.make_async_copy(
            out_ref.at[ids_ref[base + j]], tbl.at[j], rsems.at[j]
        ).wait()
        return 0

    lax.fori_loop(0, block_rows, rwait, 0)

    # Accumulate phase: sequential over the block's rows; a run of equal
    # ids sums into acc, and each run's END row materializes table+sum in
    # wb[j] (a stable per-row buffer, so write DMAs of earlier runs can
    # still be in flight) and starts its write-back.
    def body(j, _):
        gj = base + j
        # Clamp the previous-id lookup: at gj == 0 the unclamped index -1
        # would read before the scalar buffer (masked by j > 0, but the
        # read itself is out of bounds on hardware).
        prev_same = jnp.logical_and(
            j > 0, ids_ref[gj] == ids_ref[jnp.maximum(gj - 1, 0)]
        )
        cur = upd_fn(j, gj) + jnp.where(prev_same, acc[0], tbl[j])
        acc[0] = cur
        wb[j] = cur
        is_end = jnp.logical_or(
            j == block_rows - 1, ids_ref[gj + 1] != ids_ref[gj]
        )

        @pl.when(is_end)
        def _():
            pltpu.make_async_copy(
                wb.at[j], out_ref.at[ids_ref[gj]], wsems.at[j]
            ).start()

        return 0

    lax.fori_loop(0, block_rows, body, 0)

    # All writes must land before this grid step ends: the next step may
    # read a row this one wrote (a run spanning the block boundary).
    def wwait(j, _):
        gj = base + j
        is_end = jnp.logical_or(
            j == block_rows - 1, ids_ref[gj + 1] != ids_ref[gj]
        )

        @pl.when(is_end)
        def _():
            pltpu.make_async_copy(
                wb.at[j], out_ref.at[ids_ref[gj]], wsems.at[j]
            ).wait()

        return 0

    lax.fori_loop(0, block_rows, wwait, 0)


def _scatter_kernel(
    block_rows, ids_ref, upd_ref, table_ref, out_ref, tbl, wb, acc, rsems, wsems
):
    del table_ref
    _scatter_runs(
        block_rows, lambda j, gj: upd_ref[j],
        ids_ref, out_ref, tbl, wb, acc, rsems, wsems,
    )


def _scatter_rank1_kernel(
    block_rows, ids_ref, hidx_ref, coef_ref, h_ref, table_ref, out_ref,
    tbl, wb, acc, rsems, wsems,
):
    # Fused-payload variant: the update row is never materialized in HBM —
    # it is formed in VMEM as coef[j] * h[hidx[j]] with h resident whole in
    # VMEM. Same id-sorted run-summing contract (_scatter_runs).
    del table_ref

    def upd(j, gj):
        return (
            coef_ref[j, 0] * h_ref[hidx_ref[gj]].astype(jnp.float32)
        ).astype(tbl.dtype)

    _scatter_runs(
        block_rows, upd, ids_ref, out_ref, tbl, wb, acc, rsems, wsems
    )


@functools.partial(jax.jit, static_argnames=("interpret", "block_rows"))
def scatter_add_rank1(
    table: jax.Array,
    ids: jax.Array,  # (N,) target row per update
    coef: jax.Array,  # (N,) scalar coefficient per update
    h: jax.Array,  # (B, d) center vectors; row hidx[j] scales into ids[j]
    hidx: jax.Array,  # (N,) which h row each update uses
    *,
    interpret: bool = False,
    block_rows: int = 8,
):
    """``table.at[ids].add(coef[:, None] * h[hidx])`` without materializing
    the (N, d) payload in HBM: h is pinned whole in VMEM and each update row
    is formed in-register inside the scatter pipeline. This is the TPU
    kernel form of the reference's ``adjust`` wire format — scalars in,
    rank-1 updates applied at the data (mllib:422-425).

    Requires h to fit VMEM alongside the block buffers (~9.8 MB at the
    bench shape B=8192, d=300, f32); callers gate on that.
    """
    N = ids.shape[0]
    d = table.shape[1]
    Np = _pad_rows(N, block_rows)
    sid, order = lax.sort_key_val(
        ids.astype(jnp.int32), jnp.arange(N, dtype=jnp.int32)
    )
    scoef = coef.astype(jnp.float32)[order]
    shidx = hidx.astype(jnp.int32)[order]
    sid = jnp.pad(sid, (0, Np - N), mode="edge")
    scoef = jnp.pad(scoef, (0, Np - N))  # zero coef: pad adds 0 to last run
    shidx = jnp.pad(shidx, (0, Np - N))
    ids_arg = jnp.concatenate([sid, jnp.full((1,), -1, jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # ids, hidx
        grid=(Np // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, 1), lambda i, ids, hidx: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.VMEM),  # h, whole in VMEM
            pl.BlockSpec(memory_space=pl.ANY),  # table (aliased to output)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((block_rows, d), table.dtype),
            pltpu.VMEM((block_rows, d), table.dtype),
            pltpu.VMEM((1, d), table.dtype),
            pltpu.SemaphoreType.DMA((block_rows,)),
            pltpu.SemaphoreType.DMA((block_rows,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_scatter_rank1_kernel, block_rows),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={4: 0},  # table arg (after prefetch) -> output
        interpret=interpret,
    )(ids_arg, shidx, scoef.reshape(-1, 1), h, table)


@functools.partial(jax.jit, static_argnames=("interpret", "block_rows"))
def scatter_add_rows(
    table: jax.Array,
    ids: jax.Array,
    upd: jax.Array,
    *,
    interpret: bool = False,
    block_rows: int = 8,
):
    """``table.at[ids].add(upd)`` with duplicate-summing semantics, as an
    in-place (aliased) Pallas row pipeline over id-sorted updates."""
    N, d = upd.shape
    Np = _pad_rows(N, block_rows)
    sid, order = lax.sort_key_val(
        ids.astype(jnp.int32), jnp.arange(N, dtype=jnp.int32)
    )
    supd = upd.astype(table.dtype)[order]
    # Pad by extending the LAST run (edge mode) with zero updates: the pad
    # rows add 0 to the final run's sum. Padding with any other id could
    # place a second run for an already-written row inside the same block,
    # whose stale read-modify-write would overwrite that row's real update.
    sid = jnp.pad(sid, (0, Np - N), mode="edge")
    supd = jnp.pad(supd, ((0, Np - N), (0, 0)))
    # The kernel indexes ids[gj+1] for the run-end test; append a sentinel
    # (never equal to a real id) so the final run closes at the last row.
    ids_arg = jnp.concatenate([sid, jnp.full((1,), -1, jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Np // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i, ids: (i, 0)),  # updates
            pl.BlockSpec(memory_space=pl.ANY),  # table (aliased to output)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            # Same dtype as the table: these buffers are DMA endpoints for
            # its rows (copies require matching dtypes), and accumulating a
            # run in table dtype matches the XLA scatter-add's semantics.
            pltpu.VMEM((block_rows, d), table.dtype),  # tbl rows read
            pltpu.VMEM((block_rows, d), table.dtype),  # write-back buffers
            pltpu.VMEM((1, d), table.dtype),  # run accumulator
            pltpu.SemaphoreType.DMA((block_rows,)),  # read sems
            pltpu.SemaphoreType.DMA((block_rows,)),  # write sems
        ],
    )
    return pl.pallas_call(
        functools.partial(_scatter_kernel, block_rows),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={2: 0},  # table arg (after prefetch) -> output
        interpret=interpret,
    )(ids_arg, supd, table)
