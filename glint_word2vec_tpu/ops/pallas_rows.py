"""Pallas TPU kernels for the sparse row traffic of the SGNS step.

The hot sparse ops in the engine (parallel/engine.py) are row gathers
(``_pull_rows``) and rank-1 row scatter-adds (``_scatter_rows``) into the
(V, d) embedding tables — the device-side restatement of what the reference
parameter servers do inside ``dotprod``/``adjust`` (mllib:421-425). XLA
lowers them to generic gather/scatter; these kernels instead stream one
table row per grid step with the scalar-prefetch index-map pattern
(PrefetchScalarGridSpec): the row index arrives before the body runs, so
Pallas's pipeline overlaps the HBM row DMA for step i+1 with the work of
step i.

Correctness contract for the scatter: duplicate target rows must SUM their
updates (synchronous-batch semantics, SURVEY.md §7 hard part 1). Pallas
only defines output-block revisits when they are CONSECUTIVE grid steps
(the canonical accumulation pattern — the block stays resident in VMEM
until the index map moves on); a non-consecutive revisit can read a stale
copy while the earlier write's DMA is in flight. :func:`scatter_add_rows`
therefore sorts the updates by row id (duplicates become adjacent) and the
kernel accumulates into the output block across the run of equal ids:
first visit writes ``table_row + upd``, later visits add ``upd`` to the
resident block.

These kernels are OPT-IN (engine flag / GLINT_W2V_PALLAS env var): XLA's
native lowering is the default until per-hardware measurement says
otherwise. On CPU they run in interpret mode, which is how the unit tests
exercise them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gather_kernel(ids_ref, table_block, out_block):
    del ids_ref  # consumed by the index map
    out_block[:] = table_block[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_rows(table: jax.Array, ids: jax.Array, *, interpret: bool = False):
    """``table[ids]`` as a Pallas pipeline: one (1, d) row block per grid
    step, row address from the prefetched ``ids``."""
    N = ids.shape[0]
    d = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(N,),
        in_specs=[
            pl.BlockSpec((1, d), lambda i, ids: (ids[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, d), lambda i, ids: (i, 0)),
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, d), table.dtype),
        interpret=interpret,
    )(ids.astype(jnp.int32), table)


def _scatter_kernel(ids_ref, upd_block, table_block, out_block):
    # out_block aliases table_block's storage (input_output_aliases). The
    # ids are sorted, so every revisit of an output row is a CONSECUTIVE
    # grid step and the block stays resident in VMEM: the first step of a
    # run of equal ids seeds the block from the table row, later steps
    # accumulate into it.
    i = pl.program_id(0)
    prev = ids_ref[jnp.maximum(i - 1, 0)]
    is_first = jnp.logical_or(i == 0, ids_ref[i] != prev)

    @pl.when(is_first)
    def _():
        out_block[:] = table_block[:] + upd_block[:]

    @pl.when(jnp.logical_not(is_first))
    def _():
        out_block[:] = out_block[:] + upd_block[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def scatter_add_rows(
    table: jax.Array, ids: jax.Array, upd: jax.Array, *,
    interpret: bool = False,
):
    """``table.at[ids].add(upd)`` with duplicate-summing semantics, as an
    in-place (aliased) Pallas row pipeline over id-sorted updates."""
    N, d = upd.shape
    order = jnp.argsort(ids.astype(jnp.int32))
    sid = ids.astype(jnp.int32)[order]
    supd = upd.astype(table.dtype)[order]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(N,),
        in_specs=[
            pl.BlockSpec((1, d), lambda i, ids: (i, 0)),  # update row
            pl.BlockSpec((1, d), lambda i, ids: (ids[i], 0)),  # table row
        ],
        out_specs=pl.BlockSpec((1, d), lambda i, ids: (ids[i], 0)),
    )
    return pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={2: 0},  # table arg (after prefetch) -> output
        interpret=interpret,
    )(sid, supd, table)
