"""Skip-gram negative-sampling step math, as pure jit-compatible functions.

This module is the TPU restatement of the reference's hot loop: the
client/server round-trip pair ``matrix.dotprod`` (servers compute partial dot
products for positive and negative pairs, mllib:421) + ``matrix.adjust``
(servers replay cached indices and apply rank-1 SGD updates, mllib:425),
with the client-side sigmoid-LUT gradient scaling between them
(mllib:422-424). Here all three fuse into one on-device function: gather ->
batched dot products (MXU) -> exact sigmoid (documented divergence from the
reference's 1000-bin LUT, mllib:281-302 — the LUT was a CPU optimization; on
TPU the exact form is free) -> scalar gradient coefficients -> scatter-add
rank-1 updates. The RPC cache keys (``cacheKeys``) dissolve: the "cache" is
simply values held in registers/VMEM between the two halves of the fused op.

All functions are shape-polymorphic in batch B, context lanes C, negatives n,
and embedding dim d, and run identically per-shard under ``shard_map`` (the
sharded engine in parallel/engine.py supplies gathered rows and consumes
per-row updates).

Padding convention: padded context lanes / padded batch rows carry index 0
and mask 0.0; every gradient coefficient is multiplied by its mask, so
padded entries contribute exactly zero to every scatter-add.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from glint_word2vec_tpu.ops.sampling import sample_negatives_per_row


class SgnsCoefs(NamedTuple):
    """Logit-stage outputs shared by every sharding layout: the scalar SGD
    coefficients (the reference's gPlus/gMinus wire format, mllib:422-425)
    plus the monitoring loss. Computed from FULL logits — under dim/column
    sharding (parallel/engine.py layout="dims") each shard psums its
    partial dot products first, then evaluates this identically."""

    c_pos: jax.Array  # (B, C)
    c_neg: jax.Array  # (B, C, n)
    loss: jax.Array  # ()


def sgns_coefs(
    f_pos: jax.Array,  # (B, C) float32 FULL positive logits
    f_neg: jax.Array,  # (B, C, n) float32 FULL negative logits
    mask: jax.Array,  # (B, C) float32
    neg_mask: jax.Array,  # (B, C, n) float32
    alpha: jax.Array,  # () float32
) -> SgnsCoefs:
    """Coefficients + loss from already-reduced logits (layout-agnostic)."""
    s_pos = jax.nn.sigmoid(f_pos)
    s_neg = jax.nn.sigmoid(f_neg)
    c_pos = alpha * (1.0 - s_pos) * mask
    c_neg = -alpha * s_neg * neg_mask
    log_sig = jax.nn.log_sigmoid
    pair_loss = -log_sig(f_pos) * mask - jnp.sum(
        log_sig(-f_neg) * neg_mask, axis=-1
    ) * mask
    loss = pair_loss.sum() / jnp.maximum(mask.sum(), 1.0)
    return SgnsCoefs(c_pos=c_pos, c_neg=c_neg, loss=loss)


class SgnsGrads(NamedTuple):
    """Scalar gradient coefficients + center-row gradient for one minibatch.

    ``c_pos``/``c_neg`` are exactly the reference's ``gPlus``/``gMinus``
    scalars (the only payload the client ever sends back to the servers,
    mllib:422-425): the SGD coefficient multiplying the *other* side's row in
    each rank-1 update, learning rate included.
    """

    c_pos: jax.Array  # (B, C)    alpha * (1 - sigmoid(f_pos)) * mask
    c_neg: jax.Array  # (B, C, n) alpha * (0 - sigmoid(f_neg)) * mask
    d_center: jax.Array  # (B, d)  gradient w.r.t. syn0[centers]
    loss: jax.Array  # () masked-mean SGNS loss (monitoring only)


def sgns_grads(
    h: jax.Array,  # (B, d) float32 — syn0 rows of the centers
    u_pos: jax.Array,  # (B, C, d) float32 — syn1 rows of the contexts
    u_neg: jax.Array,  # (B, C, n, d) float32 — syn1 rows of the negatives
    mask: jax.Array,  # (B, C) float32 — 1.0 where the context slot is real
    neg_mask: jax.Array,  # (B, C, n) float32 — negatives kept (see train step)
    alpha: jax.Array,  # () float32 learning rate
    compute_dtype=jnp.float32,
) -> SgnsGrads:
    """Forward + backward of the SGNS objective for pre-gathered rows.

    Objective per real (center, context) pair (README.md:10-15 model):
        L = -log sigma(u_ctx . h) - sum_n log sigma(-u_neg . h)
    SGD coefficients (matching the reference's label-vs-sigmoid form at
    mllib:422-424): c_pos = alpha*(1 - sigma(f_pos)), c_neg = -alpha*sigma(f_neg).

    ``compute_dtype=bfloat16`` feeds the d-contraction einsums bf16
    operands with f32 accumulation (the MXU-native regime); coefficient
    math, masking, and the loss stay f32. Word2vec SGD tolerates the
    ~3-decimal-digit operand rounding (embeddings are trained with far
    noisier estimators); the exactness-tested default stays f32.
    """
    hc = h.astype(compute_dtype)
    upc = u_pos.astype(compute_dtype)
    unc = u_neg.astype(compute_dtype)
    f_pos = jnp.einsum(
        "bd,bcd->bc", hc, upc, preferred_element_type=jnp.float32
    )  # (B, C)
    f_neg = jnp.einsum(
        "bd,bcnd->bcn", hc, unc, preferred_element_type=jnp.float32
    )  # (B, C, n)
    co = sgns_coefs(f_pos, f_neg, mask, neg_mask, alpha)
    d_center = sgns_d_center(co.c_pos, co.c_neg, u_pos, u_neg, compute_dtype)
    return SgnsGrads(
        c_pos=co.c_pos, c_neg=co.c_neg, d_center=d_center, loss=co.loss
    )


def sgns_d_center(
    c_pos: jax.Array,  # (B, C)
    c_neg: jax.Array,  # (B, C, n)
    u_pos: jax.Array,  # (B, C, dl) — full d or a column slice of it
    u_neg: jax.Array,  # (B, C, n, dl)
    compute_dtype=jnp.float32,
) -> jax.Array:
    """d L/d h with the learning rate folded in (pure SGD step direction).
    Columnwise-independent, so a dim-sharded shard passes its local column
    slices and gets its local d_center slice — no communication."""
    return jnp.einsum(
        "bc,bcd->bd", c_pos.astype(compute_dtype),
        u_pos.astype(compute_dtype), preferred_element_type=jnp.float32,
    ) + jnp.einsum(
        "bcn,bcnd->bd", c_neg.astype(compute_dtype),
        u_neg.astype(compute_dtype), preferred_element_type=jnp.float32,
    )


def init_tables(
    key: jax.Array, vocab_size: int, dim: int, dtype=jnp.float32
) -> Tuple[jax.Array, jax.Array]:
    """word2vec-standard init: syn0 ~ U[-0.5/d, 0.5/d), syn1 = 0."""
    syn0 = (
        jax.random.uniform(key, (vocab_size, dim), dtype=jnp.float32) - 0.5
    ) / dim
    return syn0.astype(dtype), jnp.zeros((vocab_size, dim), dtype=dtype)


def negative_mask(
    negs: jax.Array,  # (B, C, n) int32
    contexts: jax.Array,  # (B, C) int32
    mask: jax.Array,  # (B, C) float32
) -> jax.Array:
    """Mask for negative draws: drop a negative that equals its positive
    context word (the standard word2vec "target == word" skip), and zero out
    draws for padded context lanes."""
    keep = (negs != contexts[..., None]).astype(jnp.float32)
    return keep * mask[..., None]


class SharedSgnsGrads(NamedTuple):
    """Gradient pieces for the shared-negative-pool estimator."""

    c_pos: jax.Array  # (B, C)  alpha * (1 - sigmoid(f_pos)) * mask
    c_pool: jax.Array  # (B, S)  weighted negative coefficients per center
    d_center: jax.Array  # (B, d)
    d_pool: jax.Array  # (S, d)  dense update for the pool's syn1 rows
    loss: jax.Array  # ()


def shared_sgns_grads(
    h: jax.Array,  # (B, d) float32 — syn0 rows of the centers
    u_pos: jax.Array,  # (B, C, d) float32 — syn1 rows of the contexts
    u_pool: jax.Array,  # (S, d) float32 — syn1 rows of the shared pool
    mask: jax.Array,  # (B, C) float32
    collide: jax.Array,  # (B, S) float32 — 1.0 where pool word hits one of
    #   the center's real context words (excluded, word2vec's target==word
    #   skip applied pool-wide)
    alpha: jax.Array,  # () float32
    num_negatives: int,  # n — the per-pair draw count being emulated
    compute_dtype=jnp.float32,
) -> SharedSgnsGrads:
    """SGNS gradients with one shared negative pool per step.

    The TPU-first restatement of negative sampling: the reference draws
    ``n`` fresh negatives per (center, context) pair server-side
    (``dotprod``'s seeded draws, mllib:420-421) — on TPU that becomes a
    gather of B*C*n arbitrary rows, a bandwidth-bound sparse access. Here
    each step draws ONE pool of S negatives shared by the whole batch and
    weights every center's pool term by ``m_i * n / S`` (m_i = its real
    context count): an unbiased Monte-Carlo estimator of the same expected
    NCE gradient (each pair still sees n expected noise draws from the
    same unigram^0.75 distribution), usually with *lower* variance since
    S >> n. All pool compute is dense:

        f_pool = h @ u_pool.T          (B, S)  MXU
        d_pool = c_pool.T @ h          (S, d)  MXU
        d_center += c_pool @ u_pool    (B, d)  MXU

    so the only sparse traffic left is the centers and positive contexts.

    ``compute_dtype=bfloat16`` runs the three dense matmuls with bf16
    operands and f32 accumulation — the MXU-native regime (v5e bf16 peak
    is ~2x its f32-via-passes rate); coefficients and loss stay f32.
    """
    hc = h.astype(compute_dtype)
    upool_c = u_pool.astype(compute_dtype)
    f_pos = jnp.einsum(
        "bd,bcd->bc", hc, u_pos.astype(compute_dtype),
        preferred_element_type=jnp.float32,
    )  # (B, C)
    f_pool = jnp.dot(
        hc, upool_c.T, preferred_element_type=jnp.float32
    )  # (B, S)
    co = shared_sgns_coefs(f_pos, f_pool, mask, collide, alpha, num_negatives)
    d_center, d_pool = shared_sgns_updates(
        co.c_pos, co.c_pool, h, u_pos, u_pool, compute_dtype
    )
    return SharedSgnsGrads(
        c_pos=co.c_pos, c_pool=co.c_pool, d_center=d_center, d_pool=d_pool,
        loss=co.loss,
    )


class SharedSgnsCoefs(NamedTuple):
    """Logit-stage outputs of the shared-pool estimator (layout-agnostic;
    see :class:`SgnsCoefs`)."""

    c_pos: jax.Array  # (B, C)
    c_pool: jax.Array  # (B, S)
    loss: jax.Array  # ()


def shared_sgns_coefs(
    f_pos: jax.Array,  # (B, C) float32 FULL positive logits
    f_pool: jax.Array,  # (B, S) float32 FULL pool logits
    mask: jax.Array,  # (B, C) float32
    collide: jax.Array,  # (B, S) float32
    alpha: jax.Array,  # () float32
    num_negatives: int,
) -> SharedSgnsCoefs:
    """Coefficients + loss from already-reduced logits."""
    s_pos = jax.nn.sigmoid(f_pos)
    s_pool = jax.nn.sigmoid(f_pool)
    m_i = mask.sum(axis=1)  # (B,) real context count per center
    S = f_pool.shape[1]
    keep = 1.0 - collide
    weight = (m_i * (num_negatives / S))[:, None] * keep  # (B, S)
    c_pos = alpha * (1.0 - s_pos) * mask
    c_pool = -alpha * s_pool * weight
    log_sig = jax.nn.log_sigmoid
    pos_loss = (-log_sig(f_pos) * mask).sum()
    pool_loss = (-log_sig(-f_pool) * weight).sum()
    loss = (pos_loss + pool_loss) / jnp.maximum(mask.sum(), 1.0)
    return SharedSgnsCoefs(c_pos=c_pos, c_pool=c_pool, loss=loss)


def shared_sgns_updates(
    c_pos: jax.Array,  # (B, C)
    c_pool: jax.Array,  # (B, S)
    h: jax.Array,  # (B, dl) — full d or a column slice
    u_pos: jax.Array,  # (B, C, dl)
    u_pool: jax.Array,  # (S, dl)
    compute_dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array]:
    """(d_center, d_pool) from coefficients — columnwise-independent, so a
    dim-sharded shard passes local column slices (see :func:`sgns_d_center`)."""
    cpool_c = c_pool.astype(compute_dtype)
    upool_c = u_pool.astype(compute_dtype)
    d_center = jnp.einsum(
        "bc,bcd->bd", c_pos.astype(compute_dtype),
        u_pos.astype(compute_dtype), preferred_element_type=jnp.float32,
    ) + jnp.dot(cpool_c, upool_c, preferred_element_type=jnp.float32)
    d_pool = jnp.dot(
        cpool_c.T, h.astype(compute_dtype), preferred_element_type=jnp.float32
    )  # (S, dl)
    return d_center, d_pool


def pool_collision_mask(
    pool: jax.Array,  # (S,) int32 — shared negative pool
    contexts: jax.Array,  # (B, C) int32
    mask: jax.Array,  # (B, C) float32
) -> jax.Array:
    """(B, S) mask, 1.0 where a pool word equals one of that row's real
    context words — the pool-wide generalization of the per-draw
    ``target == word`` skip (see :func:`negative_mask`).

    Membership is tested by sorting each row's C context words and binary-
    searching the pool into them, so the peak intermediate is O(B*S) — the
    same order as the (B, S) result — rather than the O(B*C*S) boolean of
    the naive broadcast compare (~235 MB of transient at the bench shape
    B=8192, C=7, S=4096, which could dominate step memory)."""
    sentinel = jnp.iinfo(jnp.int32).max
    ctx = jnp.where(mask > 0, contexts, sentinel)  # padded lanes never match
    ctx_sorted = jnp.sort(ctx, axis=1)  # (B, C) — C is tiny
    idx = jax.vmap(
        lambda row: jnp.searchsorted(row, pool, side="left")
    )(ctx_sorted)  # (B, S)
    idx = jnp.clip(idx, 0, ctx_sorted.shape[1] - 1)
    found = jnp.take_along_axis(ctx_sorted, idx, axis=1) == pool[None, :]
    return found.astype(jnp.float32)


def train_step(
    syn0: jax.Array,  # (V, d)
    syn1: jax.Array,  # (V, d)
    prob: jax.Array,  # (V,) alias acceptance probs
    alias: jax.Array,  # (V,) alias targets
    centers: jax.Array,  # (B,) int32
    contexts: jax.Array,  # (B, C) int32
    mask: jax.Array,  # (B, C) float32
    key: jax.Array,
    alpha: jax.Array,  # () float32
    num_negatives: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One fused single-device SGNS minibatch update.

    Returns (new_syn0, new_syn1, loss). Jit with donated syn0/syn1 for
    in-place HBM updates. Duplicate indices within a batch *sum* their
    updates (XLA scatter-add) — the synchronous-batch semantics replacing the
    reference's async Hogwild races (SURVEY.md §2.3, §7 hard part 3).
    """
    B, C = contexts.shape
    negs = sample_negatives_per_row(
        key, prob, alias, jnp.arange(B, dtype=jnp.int32), (C, num_negatives)
    )
    compute = jnp.float32
    h = syn0[centers].astype(compute)
    u_pos = syn1[contexts].astype(compute)
    u_neg = syn1[negs].astype(compute)
    nmask = negative_mask(negs, contexts, mask)

    g = sgns_grads(h, u_pos, u_neg, mask, nmask, alpha.astype(compute))

    # Rank-1 updates, scatter-added into the tables.
    d_upos = g.c_pos[..., None] * h[:, None, :]  # (B, C, d)
    d_uneg = g.c_neg[..., None] * h[:, None, None, :]  # (B, C, n, d)
    syn0 = syn0.at[centers].add(g.d_center.astype(syn0.dtype))
    syn1 = syn1.at[contexts.reshape(-1)].add(
        d_upos.reshape(B * C, -1).astype(syn1.dtype)
    )
    syn1 = syn1.at[negs.reshape(-1)].add(
        d_uneg.reshape(B * C * num_negatives, -1).astype(syn1.dtype)
    )
    return syn0, syn1, g.loss


def train_step_pairs(
    syn0: jax.Array,  # (V, d)
    syn1: jax.Array,  # (V, d)
    prob: jax.Array,  # (V,) alias acceptance probs
    alias: jax.Array,  # (V,) alias targets
    centers: jax.Array,  # (P,) int32 — one CENTER per pair row
    contexts: jax.Array,  # (P,) int32 — one CONTEXT per pair row
    pair_mask: jax.Array,  # (P,) float32 — 1.0 where the pair is real
    key: jax.Array,
    alpha: jax.Array,  # () float32
    num_negatives: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One fused SGNS update over a DENSE pair list — the packed-dispatch
    step (ops/device_batching.pack_window_pairs feeds it). Each batch row
    is exactly one (center, context) pair, i.e. the C=1 specialization of
    :func:`train_step`: no lane of the contraction is ever masked padding,
    so every dispatched FLOP is a useful pair (the pSGNScc dense-batch
    restructuring, arxiv 1604.04661). Negatives are drawn per PAIR row,
    keyed by the row's global index — the same mesh-invariant keying
    discipline as :func:`~glint_word2vec_tpu.ops.sampling
    .sample_negatives_per_row` everywhere else. Because scatter-adds sum,
    decomposing a grid batch into its valid pairs and feeding them here
    applies the identical table update (pinned by the decomposition test
    in tests/test_packed.py)."""
    return train_step(
        syn0, syn1, prob, alias, centers, contexts[:, None],
        pair_mask[:, None], key, alpha, num_negatives,
    )


def train_step_pairs_pallas(
    syn0: jax.Array,  # (V, d)
    syn1: jax.Array,  # (V, d)
    prob: jax.Array,  # (V,) alias acceptance probs
    alias: jax.Array,  # (V,) alias targets
    centers: jax.Array,  # (P,) int32
    contexts: jax.Array,  # (P,) int32
    pair_mask: jax.Array,  # (P,) float32
    key: jax.Array,
    alpha: jax.Array,  # () float32
    num_negatives: int,
    *,
    interpret: bool = False,
    block_rows: int = 8,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused-Pallas twin of :func:`train_step_pairs` (ISSUE 11): the
    identical update — same per-GLOBAL-pair-row negative draws, same
    coefficients, same duplicate-row sum semantics — executed by the
    ops/pallas_sgns megakernel instead of the XLA-composed gather ->
    dot -> rank-1 -> scatter chain, so every touched row crosses the
    HBM<->VMEM boundary once per phase and all arithmetic runs in fp32
    VMEM accumulators over fp32 OR bf16 table storage. The 3-way parity
    gate (tests/test_pallas_sgns.py) pins this function against the
    composed step and a host-NumPy oracle."""
    from glint_word2vec_tpu.ops.pallas_sgns import fused_pair_step

    P = centers.shape[0]
    negs = sample_negatives_per_row(
        key, prob, alias, jnp.arange(P, dtype=jnp.int32),
        (1, num_negatives),
    )  # (P, 1, n) — the exact draw train_step_pairs makes (C=1)
    nmask = negative_mask(
        negs, contexts[:, None], pair_mask[:, None]
    )  # (P, 1, n)
    syn0, syn1, loss_sum = fused_pair_step(
        syn0, syn1, centers, contexts, pair_mask,
        negs[:, 0, :], nmask[:, 0, :], alpha.astype(jnp.float32),
        interpret=interpret, block_rows=block_rows,
    )
    loss = loss_sum / jnp.maximum(pair_mask.sum(), 1.0)
    return syn0, syn1, loss


def sgns_loss(
    syn0: jax.Array,
    syn1: jax.Array,
    prob: jax.Array,
    alias: jax.Array,
    centers: jax.Array,
    contexts: jax.Array,
    mask: jax.Array,
    key: jax.Array,
    num_negatives: int,
) -> jax.Array:
    """Forward-only masked-mean SGNS loss (the jittable inference/eval fn)."""
    B, C = contexts.shape
    negs = sample_negatives_per_row(
        key, prob, alias, jnp.arange(B, dtype=jnp.int32), (C, num_negatives)
    )
    h = syn0[centers].astype(jnp.float32)
    u_pos = syn1[contexts].astype(jnp.float32)
    u_neg = syn1[negs].astype(jnp.float32)
    nmask = negative_mask(negs, contexts, mask)
    g = sgns_grads(h, u_pos, u_neg, mask, nmask, jnp.float32(1.0))
    return g.loss
