"""Fused Pallas SGNS pair-step megakernel (ISSUE 11).

The packed pair step (ops/sgns.train_step_pairs through the engine's
packed corpus scan) is XLA-composed: gather h/u rows -> dot -> sigmoid
-> rank-1 outer products -> scatter-add. Every touched row round-trips
HBM *between* those ops, and with bf16 tables the XLA lowering is so
gather/scatter-unfriendly that halving the row bytes HALVES throughput
instead of doubling it (BENCH_r05: 5,955 vs 12,577 words/sec per-pair).
This module fuses the whole pair update into Pallas kernels that move
each touched row across the HBM<->VMEM boundary once per phase and do
ALL arithmetic in fp32 VMEM registers regardless of the table's storage
dtype — bf16 tables become a pure bandwidth win (half the bytes per
row), with fp32 accumulation so low-precision storage never compounds
through a batch's duplicate-row sums.

Phase structure (one logical megakernel, staged as pallas_calls because
the synchronous-batch contract puts a hard barrier between the batch's
gathers and its scatters — every row value consumed by the update math
must be the PRE-batch value, ops/sgns.train_step semantics):

  1. ``pair_forward`` / ``pair_forward_shared``: per pair block, DMA the
     touched syn0/syn1 rows HBM->VMEM (block-DMA machinery of
     ops/pallas_rows.py), upcast to fp32 in VMEM, run dot -> sigmoid ->
     coefficient math, and emit ONLY the compact results: the scalar
     coefficients (the reference's gPlus/gMinus wire format), the center
     rows ``h`` (fp32), the center gradient ``d_center`` (fp32), and the
     summed monitoring loss. The (P, n, d) negative rows and the (P, S)
     pool logits never touch HBM — they live and die in VMEM. In shared
     mode the negative pool is DMA'd once, pinned in VMEM for the whole
     grid, and both pool contractions run as dense level-3 BLAS blocks
     on the MXU (the pSGNScc restructuring, arXiv:1611.06172):
     ``f_pool = h_blk @ pool^T`` and ``d_pool += c_pool^T @ h_blk``.
  2. ``scatter_add_rank1_hbm`` (syn1: contexts + per-pair negatives, or
     contexts alone in shared mode) and ``scatter_add_rows_f32`` (syn0:
     d_center rows; shared mode adds the dense pool payload): id-sorted
     run-summing scatters extending ops/pallas_rows._scatter_runs with
     (a) fp32 VMEM run accumulators over any-dtype tables (the composed
     XLA path scatter-adds bf16 tables IN bf16 — each duplicate row
     collision re-rounds; here a run is summed in fp32 and rounded to
     storage once per write-back, i.e. once per block it spans) and
     (b) rank-1 payloads formed in VMEM from
     ``coef * h[hidx]`` with ``h`` streamed per-row from HBM, so the
     (N, d) update payload never materializes. Exactly one accumulated
     read-modify-write lands per row run; runs spanning grid-step
     boundaries are two ordered RMWs of the same row (TPU grid steps on
     a core are sequential and every write DMA is waited before the
     step ends) — still a sum.

The only HBM intermediates between the phases are the (P,) coefficient
vectors and the two (P, d) fp32 arrays ``h`` and ``d_center`` (both
members of the minimal cut: the syn1 payload needs pre-update syn0 rows
and the syn0 payload needs pre-update syn1 rows, so whichever scatter
runs second cannot re-gather its payload source — see the ordering note
on :func:`fused_pair_step`). The composed path materializes those PLUS
u_pos (P, d), u_neg (P, n, d), and both expanded rank-1 payloads.

Like ops/pallas_rows.py these kernels are OPT-IN (engine flag /
``GLINT_W2V_PALLAS``) and run in interpret mode off-TPU, which is how
the parity tests (tests/test_pallas_sgns.py, 3-way vs the composed XLA
step and a host-NumPy oracle) exercise them on the CPU mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: VMEM budget for pinning the shared negative pool (storage + fp32
#: copies) in the shared-mode forward kernel, alongside the block
#: buffers: ~16 MB/core minus headroom. Callers gate with
#: :func:`shared_pool_vmem_ok` and fall back to the composed path.
_POOL_VMEM_BYTES = 10_000_000

#: DMA semaphores used to pipeline the one-time pool row fetch.
_POOL_DMA_PIPELINE = 8


def _pad_rows(n: int, block_rows: int) -> int:
    return -(-n // block_rows) * block_rows


def shared_pool_vmem_ok(pool_size: int, dim: int, table_dtype) -> bool:
    """Whether a shared pool of this geometry fits the forward kernel's
    VMEM budget (pool rows in table dtype + the fp32 working copy +
    the (S, d) fp32 d_pool accumulator block)."""
    itemsize = jnp.dtype(table_dtype).itemsize
    return pool_size * dim * (itemsize + 4 + 4) <= _POOL_VMEM_BYTES


# ----------------------------------------------------------------------
# Phase 1: forward (gather + dot + sigmoid + coefficient math in VMEM)
# ----------------------------------------------------------------------


class PairForward(NamedTuple):
    """Compact forward outputs of one dense pair batch — everything the
    scatter phase (and the loss record) needs, and nothing row-shaped
    beyond the two (P, d) members of the minimal phase cut."""

    c_pos: jax.Array  # (P,)   alpha * (1 - sigmoid(f_pos)) * mask
    c_neg: jax.Array  # (P, n) -alpha * sigmoid(f_neg) * nmask
    h: jax.Array  # (P, d) fp32 — pre-update syn0 rows of the centers
    d_center: jax.Array  # (P, d) fp32 — LR-folded center gradient
    loss_sum: jax.Array  # () summed pair loss (masked; divide by mask.sum())


def _pair_forward_kernel(
    block_rows, n,
    centers_ref, contexts_ref, negs_ref,  # scalar-prefetched ids
    mask_ref, nmask_ref, alpha_ref, syn0_ref, syn1_ref,  # inputs
    cpos_ref, cneg_ref, h_ref, dcen_ref, loss_ref,  # outputs
    hbuf, ubuf, nbuf, sems,  # scratch
):
    i = pl.program_id(0)
    base = i * block_rows

    # One DMA per touched row: h, u_pos, and the n negatives per pair,
    # all in flight together (block_rows * (2 + n) copies).
    def start(j, _):
        pltpu.make_async_copy(
            syn0_ref.at[centers_ref[base + j]], hbuf.at[j], sems.at[j, 0]
        ).start()
        pltpu.make_async_copy(
            syn1_ref.at[contexts_ref[base + j]], ubuf.at[j], sems.at[j, 1]
        ).start()

        def neg_start(k, _):
            pltpu.make_async_copy(
                syn1_ref.at[negs_ref[(base + j) * n + k]],
                nbuf.at[j * n + k], sems.at[j, 2 + k],
            ).start()
            return 0

        lax.fori_loop(0, n, neg_start, 0)
        return 0

    lax.fori_loop(0, block_rows, start, 0)

    def wait(j, _):
        pltpu.make_async_copy(
            syn0_ref.at[centers_ref[base + j]], hbuf.at[j], sems.at[j, 0]
        ).wait()
        pltpu.make_async_copy(
            syn1_ref.at[contexts_ref[base + j]], ubuf.at[j], sems.at[j, 1]
        ).wait()

        def neg_wait(k, _):
            pltpu.make_async_copy(
                syn1_ref.at[negs_ref[(base + j) * n + k]],
                nbuf.at[j * n + k], sems.at[j, 2 + k],
            ).wait()
            return 0

        lax.fori_loop(0, n, neg_wait, 0)
        return 0

    lax.fori_loop(0, block_rows, wait, 0)

    # All arithmetic in fp32 — the rows were DMA'd in table dtype and
    # upcast here, once, in VMEM (the mixed-precision contract).
    hb = hbuf[...].astype(jnp.float32)  # (Bk, d)
    ub = ubuf[...].astype(jnp.float32)  # (Bk, d)
    nb = nbuf[...].astype(jnp.float32).reshape(
        block_rows, n, hb.shape[-1]
    )  # (Bk, n, d)
    mask = mask_ref[...][:, 0]  # (Bk,)
    nmask = nmask_ref[...]  # (Bk, n)
    alpha = alpha_ref[0, 0]

    f_pos = jnp.sum(hb * ub, axis=-1)  # (Bk,)
    f_neg = jnp.sum(hb[:, None, :] * nb, axis=-1)  # (Bk, n)
    c_pos = alpha * (1.0 - jax.nn.sigmoid(f_pos)) * mask
    c_neg = -alpha * jax.nn.sigmoid(f_neg) * nmask
    d_center = c_pos[:, None] * ub + jnp.sum(
        c_neg[:, :, None] * nb, axis=1
    )  # (Bk, d)
    log_sig = jax.nn.log_sigmoid
    pair_loss = (
        -log_sig(f_pos) - jnp.sum(log_sig(-f_neg) * nmask, axis=-1)
    ) * mask

    cpos_ref[...] = c_pos[:, None]
    cneg_ref[...] = c_neg
    h_ref[...] = hb
    dcen_ref[...] = d_center

    @pl.when(i == 0)
    def _():
        loss_ref[0, 0] = 0.0

    loss_ref[0, 0] += jnp.sum(pair_loss)


@functools.partial(jax.jit, static_argnames=("interpret", "block_rows"))
def pair_forward(
    syn0: jax.Array,  # (V, d) table (fp32 or bf16 storage)
    syn1: jax.Array,  # (V, d)
    centers: jax.Array,  # (P,) int32
    contexts: jax.Array,  # (P,) int32
    mask: jax.Array,  # (P,) float32 — 1.0 where the pair is real
    negs: jax.Array,  # (P, n) int32 — per-pair negative draws
    nmask: jax.Array,  # (P, n) float32 — negatives kept
    alpha: jax.Array,  # () float32
    *,
    interpret: bool = False,
    block_rows: int = 8,
) -> PairForward:
    """Forward half of the fused pair step (per-pair negatives)."""
    P = centers.shape[0]
    n = negs.shape[1]
    d = syn0.shape[1]
    Pp = _pad_rows(P, block_rows)
    padn = (0, Pp - P)
    centers_p = jnp.pad(centers.astype(jnp.int32), padn)
    contexts_p = jnp.pad(contexts.astype(jnp.int32), padn)
    negs_p = jnp.pad(negs.astype(jnp.int32), (padn, (0, 0))).reshape(-1)
    mask_p = jnp.pad(mask.astype(jnp.float32), padn).reshape(-1, 1)
    nmask_p = jnp.pad(nmask.astype(jnp.float32), (padn, (0, 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # centers, contexts, flat negatives
        grid=(Pp // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, 1), lambda i, *_: (i, 0)),  # mask
            pl.BlockSpec((block_rows, n), lambda i, *_: (i, 0)),  # nmask
            pl.BlockSpec(memory_space=pltpu.SMEM),  # alpha (1, 1)
            pl.BlockSpec(memory_space=pl.ANY),  # syn0 stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),  # syn1 stays in HBM
        ],
        out_specs=[
            pl.BlockSpec((block_rows, 1), lambda i, *_: (i, 0)),  # c_pos
            pl.BlockSpec((block_rows, n), lambda i, *_: (i, 0)),  # c_neg
            pl.BlockSpec((block_rows, d), lambda i, *_: (i, 0)),  # h
            pl.BlockSpec((block_rows, d), lambda i, *_: (i, 0)),  # d_center
            pl.BlockSpec((1, 1), lambda i, *_: (0, 0)),  # loss accumulator
        ],
        scratch_shapes=[
            pltpu.VMEM((block_rows, d), syn0.dtype),  # h rows (table dtype)
            pltpu.VMEM((block_rows, d), syn1.dtype),  # u_pos rows
            pltpu.VMEM((block_rows * n, d), syn1.dtype),  # negative rows
            pltpu.SemaphoreType.DMA((block_rows, 2 + n)),
        ],
    )
    c_pos, c_neg, h, d_center, loss = pl.pallas_call(
        functools.partial(_pair_forward_kernel, block_rows, n),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((Pp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Pp, n), jnp.float32),
            jax.ShapeDtypeStruct((Pp, d), jnp.float32),
            jax.ShapeDtypeStruct((Pp, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        centers_p, contexts_p, negs_p,
        mask_p, nmask_p, alpha.astype(jnp.float32).reshape(1, 1),
        syn0, syn1,
    )
    return PairForward(
        c_pos=c_pos[:P, 0], c_neg=c_neg[:P], h=h[:P],
        d_center=d_center[:P], loss_sum=loss[0, 0],
    )


class SharedPairForward(NamedTuple):
    """Forward outputs of the shared-negative-pool estimator. The
    (P, S) pool coefficient matrix never leaves VMEM — its two uses
    (d_center's pool term, the dense d_pool payload) are contracted
    in-kernel on the MXU."""

    c_pos: jax.Array  # (P,)
    h: jax.Array  # (P, d) fp32
    d_center: jax.Array  # (P, d) fp32
    d_pool: jax.Array  # (S, d) fp32 — dense update for the pool rows
    loss_sum: jax.Array  # ()


def _pair_forward_shared_kernel(
    block_rows, n, pool_size,
    centers_ref, contexts_ref, pool_ref,  # scalar-prefetched ids
    mask_ref, pool_ids_ref, alpha_ref, syn0_ref, syn1_ref,  # inputs
    cpos_ref, h_ref, dcen_ref, dpool_ref, loss_ref,  # outputs
    hbuf, ubuf, poolbuf, pool32, sems, psems,  # scratch
):
    i = pl.program_id(0)
    base = i * block_rows
    S = pool_size

    # One-time pool staging: DMA the S pool rows HBM->VMEM at grid step
    # 0 (pipelined over a small semaphore ring) and pin the fp32 copy
    # for the whole grid — every later step reuses it from VMEM.
    @pl.when(i == 0)
    def _():
        def pstart(j, _):
            @pl.when(j >= _POOL_DMA_PIPELINE)
            def _():
                k = j - _POOL_DMA_PIPELINE
                pltpu.make_async_copy(
                    syn1_ref.at[pool_ref[k]], poolbuf.at[k],
                    psems.at[k % _POOL_DMA_PIPELINE],
                ).wait()

            pltpu.make_async_copy(
                syn1_ref.at[pool_ref[j]], poolbuf.at[j],
                psems.at[j % _POOL_DMA_PIPELINE],
            ).start()
            return 0

        lax.fori_loop(0, S, pstart, 0)

        # Drain: pstart already waited copies 0 .. S-1-PIPELINE (each
        # j >= PIPELINE waits j - PIPELINE before reusing its slot), so
        # the still-pending copies are exactly the LAST min(PIPELINE, S)
        # — wait those, starting at max(0, S - PIPELINE). (An earlier
        # form indexed S - PIPELINE + j with a >= 0 guard, which for
        # S < PIPELINE silently skipped the tail copies — interpret
        # mode executes copies synchronously and can never catch it.)
        drain_n = min(_POOL_DMA_PIPELINE, S)
        drain_0 = max(0, S - _POOL_DMA_PIPELINE)

        def pdrain(j, _):
            k = drain_0 + j
            pltpu.make_async_copy(
                syn1_ref.at[pool_ref[k]], poolbuf.at[k],
                psems.at[k % _POOL_DMA_PIPELINE],
            ).wait()
            return 0

        lax.fori_loop(0, drain_n, pdrain, 0)
        pool32[...] = poolbuf[...].astype(jnp.float32)
        dpool_ref[...] = jnp.zeros_like(dpool_ref[...])

    def start(j, _):
        pltpu.make_async_copy(
            syn0_ref.at[centers_ref[base + j]], hbuf.at[j], sems.at[j, 0]
        ).start()
        pltpu.make_async_copy(
            syn1_ref.at[contexts_ref[base + j]], ubuf.at[j], sems.at[j, 1]
        ).start()
        return 0

    lax.fori_loop(0, block_rows, start, 0)

    def wait(j, _):
        pltpu.make_async_copy(
            syn0_ref.at[centers_ref[base + j]], hbuf.at[j], sems.at[j, 0]
        ).wait()
        pltpu.make_async_copy(
            syn1_ref.at[contexts_ref[base + j]], ubuf.at[j], sems.at[j, 1]
        ).wait()
        return 0

    lax.fori_loop(0, block_rows, wait, 0)

    hb = hbuf[...].astype(jnp.float32)  # (Bk, d)
    ub = ubuf[...].astype(jnp.float32)  # (Bk, d)
    pool = pool32[...]  # (S, d) fp32, pinned
    mask = mask_ref[...][:, 0]  # (Bk,)
    pool_ids = pool_ids_ref[...][:, 0]  # (S,)
    alpha = alpha_ref[0, 0]

    f_pos = jnp.sum(hb * ub, axis=-1)  # (Bk,)
    # Level-3 BLAS pool block: one MXU matmul scores the whole block
    # against the whole pool.
    f_pool = jnp.dot(
        hb, pool.T, preferred_element_type=jnp.float32
    )  # (Bk, S)
    # Pool-wide target==word skip, C=1 form: a pool word colliding with
    # THE context word of the pair is dropped (ops/sgns
    # .pool_collision_mask restated for pair rows, computed in VMEM —
    # the (P, S) mask never materializes in HBM).
    ctx_ids = _block_ctx_ids(contexts_ref, base, block_rows)  # (Bk,)
    keep = (pool_ids[None, :] != ctx_ids[:, None]).astype(jnp.float32)
    weight = (mask * (n / S))[:, None] * keep  # (Bk, S)
    c_pos = alpha * (1.0 - jax.nn.sigmoid(f_pos)) * mask
    c_pool = -alpha * jax.nn.sigmoid(f_pool) * weight  # (Bk, S) VMEM-only
    d_center = c_pos[:, None] * ub + jnp.dot(
        c_pool, pool, preferred_element_type=jnp.float32
    )  # (Bk, d)
    log_sig = jax.nn.log_sigmoid
    loss_blk = jnp.sum(-log_sig(f_pos) * mask) + jnp.sum(
        -log_sig(-f_pool) * weight
    )

    cpos_ref[...] = c_pos[:, None]
    h_ref[...] = hb
    dcen_ref[...] = d_center
    # Dense pool gradient, accumulated across grid steps in the output
    # block (constant index map -> the block stays resident in VMEM):
    # d_pool += c_pool^T @ h_blk, the second MXU contraction.
    dpool_ref[...] += jnp.dot(
        c_pool.T, hb, preferred_element_type=jnp.float32
    )

    @pl.when(i == 0)
    def _():
        loss_ref[0, 0] = 0.0

    loss_ref[0, 0] += loss_blk


def _block_ctx_ids(contexts_ref, base, block_rows):
    """Read this block's context ids out of the scalar-prefetch ref as
    a (block_rows,) vector (SMEM scalars gathered by a tiny loop — the
    ids are already on-chip)."""
    def body(j, acc):
        return acc.at[j].set(contexts_ref[base + j])

    return lax.fori_loop(
        0, block_rows, body, jnp.zeros((block_rows,), jnp.int32)
    )


@functools.partial(
    jax.jit, static_argnames=("num_negatives", "interpret", "block_rows")
)
def pair_forward_shared(
    syn0: jax.Array,  # (V, d)
    syn1: jax.Array,  # (V, d)
    centers: jax.Array,  # (P,) int32
    contexts: jax.Array,  # (P,) int32
    mask: jax.Array,  # (P,) float32
    pool: jax.Array,  # (S,) int32 — the step's shared negative pool
    alpha: jax.Array,  # () float32
    num_negatives: int,  # n being emulated (weight n/S per pool word)
    *,
    interpret: bool = False,
    block_rows: int = 8,
) -> SharedPairForward:
    """Forward half of the fused pair step, shared-pool estimator."""
    P = centers.shape[0]
    S = pool.shape[0]
    d = syn0.shape[1]
    Pp = _pad_rows(P, block_rows)
    padn = (0, Pp - P)
    centers_p = jnp.pad(centers.astype(jnp.int32), padn)
    contexts_p = jnp.pad(contexts.astype(jnp.int32), padn)
    mask_p = jnp.pad(mask.astype(jnp.float32), padn).reshape(-1, 1)
    pool_i = pool.astype(jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # centers, contexts, pool
        grid=(Pp // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, 1), lambda i, *_: (i, 0)),  # mask
            pl.BlockSpec(memory_space=pltpu.VMEM),  # pool ids (S, 1)
            pl.BlockSpec(memory_space=pltpu.SMEM),  # alpha (1, 1)
            pl.BlockSpec(memory_space=pl.ANY),  # syn0
            pl.BlockSpec(memory_space=pl.ANY),  # syn1
        ],
        out_specs=[
            pl.BlockSpec((block_rows, 1), lambda i, *_: (i, 0)),  # c_pos
            pl.BlockSpec((block_rows, d), lambda i, *_: (i, 0)),  # h
            pl.BlockSpec((block_rows, d), lambda i, *_: (i, 0)),  # d_center
            pl.BlockSpec((S, d), lambda i, *_: (0, 0)),  # d_pool (resident)
            pl.BlockSpec((1, 1), lambda i, *_: (0, 0)),  # loss
        ],
        scratch_shapes=[
            pltpu.VMEM((block_rows, d), syn0.dtype),
            pltpu.VMEM((block_rows, d), syn1.dtype),
            pltpu.VMEM((S, d), syn1.dtype),  # pool rows, table dtype
            pltpu.VMEM((S, d), jnp.float32),  # pool rows, fp32 pinned
            pltpu.SemaphoreType.DMA((block_rows, 2)),
            pltpu.SemaphoreType.DMA((_POOL_DMA_PIPELINE,)),
        ],
    )
    c_pos, h, d_center, d_pool, loss = pl.pallas_call(
        functools.partial(
            _pair_forward_shared_kernel, block_rows,
            # graftlint: ignore[sync-point] static python config int -> float for the kernel closure
            float(num_negatives), S,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((Pp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Pp, d), jnp.float32),
            jax.ShapeDtypeStruct((Pp, d), jnp.float32),
            jax.ShapeDtypeStruct((S, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        centers_p, contexts_p, pool_i,
        mask_p, pool_i.reshape(-1, 1),
        alpha.astype(jnp.float32).reshape(1, 1),
        syn0, syn1,
    )
    return SharedPairForward(
        c_pos=c_pos[:P, 0], h=h[:P], d_center=d_center[:P],
        d_pool=d_pool, loss_sum=loss[0, 0],
    )


# ----------------------------------------------------------------------
# Phase 2: id-sorted run-summing scatters with fp32 VMEM accumulation
# ----------------------------------------------------------------------


def _scatter_runs_f32(
    block_rows, upd_fn, ids_ref, out_ref, tbl, wb, acc, rsems, wsems
):
    """ops/pallas_rows._scatter_runs generalized to MIXED precision:
    the table rows are DMA'd in storage dtype, each run of equal
    (globally sorted) ids is summed in a fp32 VMEM accumulator, and the
    run total is rounded to storage dtype exactly once at its single
    read-modify-write. ``upd_fn(j, gj) -> fp32 row`` produces update
    row j (block-local) / gj (global)."""
    base = pl.program_id(0) * block_rows

    def rstart(j, _):
        pltpu.make_async_copy(
            out_ref.at[ids_ref[base + j]], tbl.at[j], rsems.at[j]
        ).start()
        return 0

    lax.fori_loop(0, block_rows, rstart, 0)

    def rwait(j, _):
        pltpu.make_async_copy(
            out_ref.at[ids_ref[base + j]], tbl.at[j], rsems.at[j]
        ).wait()
        return 0

    lax.fori_loop(0, block_rows, rwait, 0)

    def body(j, _):
        gj = base + j
        prev_same = jnp.logical_and(
            j > 0, ids_ref[gj] == ids_ref[jnp.maximum(gj - 1, 0)]
        )
        cur = upd_fn(j, gj) + jnp.where(
            prev_same, acc[0], tbl[j].astype(jnp.float32)
        )
        acc[0] = cur
        wb[j] = cur.astype(wb.dtype)
        is_end = jnp.logical_or(
            j == block_rows - 1, ids_ref[gj + 1] != ids_ref[gj]
        )

        @pl.when(is_end)
        def _():
            pltpu.make_async_copy(
                wb.at[j], out_ref.at[ids_ref[gj]], wsems.at[j]
            ).start()

        return 0

    lax.fori_loop(0, block_rows, body, 0)

    # All writes land before the grid step ends: a run spanning the
    # block boundary is the next step's first read of this row.
    def wwait(j, _):
        gj = base + j
        is_end = jnp.logical_or(
            j == block_rows - 1, ids_ref[gj + 1] != ids_ref[gj]
        )

        @pl.when(is_end)
        def _():
            pltpu.make_async_copy(
                wb.at[j], out_ref.at[ids_ref[gj]], wsems.at[j]
            ).wait()

        return 0

    lax.fori_loop(0, block_rows, wwait, 0)


def _scatter_rows_f32_kernel(
    block_rows, ids_ref, upd_ref, table_ref, out_ref,
    tbl, wb, acc, rsems, wsems,
):
    del table_ref
    _scatter_runs_f32(
        block_rows, lambda j, gj: upd_ref[j],
        ids_ref, out_ref, tbl, wb, acc, rsems, wsems,
    )


def _sorted_scatter_args(ids, N, block_rows):
    """Shared sort/pad plumbing: globally sort ids (duplicates become
    contiguous runs), pad by EXTENDING the last run (edge mode — pad
    rows add zero to the final run's sum; any other id could open a
    second run for an already-written row inside one block), and append
    the -1 sentinel the run-end test reads at gj + 1."""
    Np = _pad_rows(N, block_rows)
    sid, order = lax.sort_key_val(
        ids.astype(jnp.int32), jnp.arange(N, dtype=jnp.int32)
    )
    sid = jnp.pad(sid, (0, Np - N), mode="edge")
    ids_arg = jnp.concatenate([sid, jnp.full((1,), -1, jnp.int32)])
    return Np, order, ids_arg


@functools.partial(jax.jit, static_argnames=("interpret", "block_rows"))
def scatter_add_rows_f32(
    table: jax.Array,  # (V, d) storage dtype (fp32 or bf16)
    ids: jax.Array,  # (N,) target row per update
    upd: jax.Array,  # (N, d) fp32 update rows
    *,
    interpret: bool = False,
    block_rows: int = 8,
):
    """``table.at[ids].add(upd)`` with duplicate-run sums accumulated in
    fp32 VMEM and exactly one storage-dtype read-modify-write per row
    run — the mixed-precision scatter of the fused pair step."""
    N, d = upd.shape
    Np, order, ids_arg = _sorted_scatter_args(ids, N, block_rows)
    supd = jnp.pad(
        upd.astype(jnp.float32)[order], ((0, Np - N), (0, 0))
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Np // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i, ids: (i, 0)),  # updates
            pl.BlockSpec(memory_space=pl.ANY),  # table (aliased)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((block_rows, d), table.dtype),  # rows read
            pltpu.VMEM((block_rows, d), table.dtype),  # write-back
            pltpu.VMEM((1, d), jnp.float32),  # fp32 run accumulator
            pltpu.SemaphoreType.DMA((block_rows,)),
            pltpu.SemaphoreType.DMA((block_rows,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_scatter_rows_f32_kernel, block_rows),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={2: 0},  # table arg (after prefetch) -> out
        interpret=interpret,
    )(ids_arg, supd, table)


def _scatter_rank1_hbm_kernel(
    block_rows, ids_ref, hidx_ref, coef_ref, h_ref, table_ref, out_ref,
    tbl, hbuf, wb, acc, rsems, hsems, wsems,
):
    # Rank-1 payload with h streamed from HBM: row j's payload is
    # coef[j] * h[hidx[j]], formed in VMEM after a per-row DMA of the
    # fp32 h row — no VMEM-resident copy of the whole h, so P is
    # unbounded (scatter_add_rank1 pins h whole and gates on its size).
    del table_ref
    base = pl.program_id(0) * block_rows

    def hstart(j, _):
        pltpu.make_async_copy(
            h_ref.at[hidx_ref[base + j]], hbuf.at[j], hsems.at[j]
        ).start()
        return 0

    lax.fori_loop(0, block_rows, hstart, 0)

    def hwait(j, _):
        pltpu.make_async_copy(
            h_ref.at[hidx_ref[base + j]], hbuf.at[j], hsems.at[j]
        ).wait()
        return 0

    lax.fori_loop(0, block_rows, hwait, 0)

    _scatter_runs_f32(
        block_rows, lambda j, gj: coef_ref[j, 0] * hbuf[j],
        ids_ref, out_ref, tbl, wb, acc, rsems, wsems,
    )


@functools.partial(jax.jit, static_argnames=("interpret", "block_rows"))
def scatter_add_rank1_hbm(
    table: jax.Array,  # (V, d) storage dtype
    ids: jax.Array,  # (N,) target row per update
    coef: jax.Array,  # (N,) fp32 scalar coefficient per update
    h: jax.Array,  # (B, d) fp32 center rows (stays in HBM)
    hidx: jax.Array,  # (N,) which h row each update scales
    *,
    interpret: bool = False,
    block_rows: int = 8,
):
    """``table.at[ids].add(coef[:, None] * h[hidx])`` without ever
    materializing the (N, d) payload: h rows are DMA'd per update row,
    the product is formed in VMEM, runs are summed in fp32, and one
    storage-dtype read-modify-write lands per row run."""
    N = ids.shape[0]
    d = table.shape[1]
    Np, order, ids_arg = _sorted_scatter_args(ids, N, block_rows)
    scoef = jnp.pad(
        coef.astype(jnp.float32)[order], (0, Np - N)
    )  # zero coef: pad rows add 0 to the last run
    shidx = jnp.pad(hidx.astype(jnp.int32)[order], (0, Np - N))
    h32 = h.astype(jnp.float32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # ids, hidx
        grid=(Np // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, 1), lambda i, *_: (i, 0)),  # coef
            pl.BlockSpec(memory_space=pl.ANY),  # h stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),  # table (aliased)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((block_rows, d), table.dtype),  # rows read
            pltpu.VMEM((block_rows, d), jnp.float32),  # h rows
            pltpu.VMEM((block_rows, d), table.dtype),  # write-back
            pltpu.VMEM((1, d), jnp.float32),  # fp32 run accumulator
            pltpu.SemaphoreType.DMA((block_rows,)),
            pltpu.SemaphoreType.DMA((block_rows,)),
            pltpu.SemaphoreType.DMA((block_rows,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_scatter_rank1_hbm_kernel, block_rows),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={4: 0},  # table arg (after prefetch) -> out
        interpret=interpret,
    )(ids_arg, shidx, scoef.reshape(-1, 1), h32, table)


# ----------------------------------------------------------------------
# The fused pair step (forward + scatters, both estimators)
# ----------------------------------------------------------------------


def fused_pair_step(
    syn0: jax.Array,
    syn1: jax.Array,
    centers: jax.Array,  # (P,) int32
    contexts: jax.Array,  # (P,) int32
    pair_mask: jax.Array,  # (P,) float32
    negs: jax.Array,  # (P, n) int32
    nmask: jax.Array,  # (P, n) float32
    alpha: jax.Array,  # () float32
    *,
    interpret: bool = False,
    block_rows: int = 8,
):
    """One fused dense-pair SGNS update (per-pair negatives). Returns
    ``(new_syn0, new_syn1, loss_sum)`` — the un-normalized summed loss;
    callers divide by ``pair_mask.sum()`` (the engine's global masked
    mean needs the sum form for its data-axis psum).

    Scatter ordering: syn1 first (its rank-1 payload reads the
    MATERIALIZED pre-update ``h``, never live syn0), then syn0 from the
    materialized ``d_center``. Neither scatter re-gathers from a table
    the other has already modified — the two (P, d) intermediates exist
    precisely to cut that dependency, preserving the composed step's
    all-gathers-before-all-scatters semantics.
    """
    P = centers.shape[0]
    n = negs.shape[1]
    fw = pair_forward(
        syn0, syn1, centers, contexts, pair_mask, negs, nmask, alpha,
        interpret=interpret, block_rows=block_rows,
    )
    rows = jnp.arange(P, dtype=jnp.int32)
    ids1 = jnp.concatenate([contexts.astype(jnp.int32), negs.reshape(-1)])
    coefs = jnp.concatenate([fw.c_pos, fw.c_neg.reshape(-1)])
    hidx = jnp.concatenate([rows, jnp.repeat(rows, n)])
    syn1 = scatter_add_rank1_hbm(
        syn1, ids1, coefs, fw.h, hidx,
        interpret=interpret, block_rows=block_rows,
    )
    syn0 = scatter_add_rows_f32(
        syn0, centers, fw.d_center,
        interpret=interpret, block_rows=block_rows,
    )
    return syn0, syn1, fw.loss_sum


def fused_pair_step_shared(
    syn0: jax.Array,
    syn1: jax.Array,
    centers: jax.Array,  # (P,) int32
    contexts: jax.Array,  # (P,) int32
    pair_mask: jax.Array,  # (P,) float32
    pool: jax.Array,  # (S,) int32
    alpha: jax.Array,  # () float32
    num_negatives: int,
    *,
    interpret: bool = False,
    block_rows: int = 8,
):
    """Shared-pool form of :func:`fused_pair_step`: the pool update is
    the dense (S, d) ``d_pool`` block computed on the MXU in the
    forward kernel, landed with the same run-summing fp32 scatter (pool
    ids may repeat — the alias draw is with replacement — and a pool
    word can also appear as a context: ordered RMWs still sum)."""
    P = centers.shape[0]
    fw = pair_forward_shared(
        syn0, syn1, centers, contexts, pair_mask, pool, alpha,
        num_negatives, interpret=interpret, block_rows=block_rows,
    )
    syn1 = scatter_add_rank1_hbm(
        syn1, contexts, fw.c_pos, fw.h, jnp.arange(P, dtype=jnp.int32),
        interpret=interpret, block_rows=block_rows,
    )
    syn1 = scatter_add_rows_f32(
        syn1, pool, fw.d_pool,
        interpret=interpret, block_rows=block_rows,
    )
    syn0 = scatter_add_rows_f32(
        syn0, centers, fw.d_center,
        interpret=interpret, block_rows=block_rows,
    )
    return syn0, syn1, fw.loss_sum
