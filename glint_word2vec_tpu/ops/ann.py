"""Device-resident two-stage approximate top-k (IVF) for serving.

The exact ``/synonyms`` path scores every vocabulary row per query — an
O(V·d) masked GEMM whose cost grows with exactly the thing PR 11's bf16
tables doubled (vocab per chip). This module is the sub-linear
replacement (ROADMAP item 2): the pSGNScc batching discipline
(arXiv:1611.06172) applied to the query side — per-query row sweeps
become small dense GEMMs over a learned coarse structure.

Stage A — coarse quantizer: spherical k-means centroids trained
ON-DEVICE from the live table by a few jitted GEMM sweeps (fixed
iteration count, fixed block shapes — the whole build is compile-once;
rebuilds reuse every program because tables/centroids arrive as traced
ARGUMENTS, never closures). A query's coarse scores ``q @ centroids.T``
pick its ``nprobe`` clusters.

Stage B — exact rerank inside the probed clusters: cluster members live
in a padded ``(C, L)`` layout whose slot count ``L`` is a FIXED function
of the engine's row capacity (``member_slots``), so every rebuild — and
every hot-swapped generation — lands in the same compiled shapes.
Clusters larger than ``L`` spill their overflow members to the next-best
cluster with space (total capacity is ~2x the table, so packing always
succeeds); a spilled row costs a little recall, never correctness, and
``nprobe == C`` degenerates to the exact masked top-k (every member slot
scored — the property the parity tests pin).

Per query the work is ``C·d`` (coarse) + ``nprobe·L·d`` (rerank) —
O(√V·d)-ish at the default geometry (``auto_clusters`` ≈ √V) versus
``V·d`` exact.

The index is a value (:class:`AnnIndex`): build against any table
(live or staged), then flip it in together with the tables under the
serving device lock — the swap-native lifecycle (ISSUE 12). Incremental
maintenance (:func:`add_rows` / :func:`remove_rows` / :func:`update_rows`)
re-buckets ONLY touched rows — the streaming-promotion path — by editing
small host masters and re-staging the ``(C, L)`` device arrays.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from glint_word2vec_tpu.utils import next_pow2

#: Row-block width of the k-means assignment/update sweeps and of the
#: full-table assignment pass: bounds the (block, C) score matrix on
#: device, and fixes the sweep's compiled shapes for any sample size.
ASSIGN_BLOCK = 8192

#: Fixed chunk of the incremental (re-)assignment path: promotion
#: bursts re-bucket in padded chunks of this many rows, so a lifetime
#: of arbitrary burst sizes compiles exactly one assign program.
INCREMENTAL_BLOCK = 256

#: Member-slot headroom: total index capacity is ``~SLOT_FACTOR x`` the
#: table's row count, split evenly across clusters. 1.5 keeps rerank
#: cost low while leaving enough slack that k-means imbalance spills
#: only the tail of oversized clusters.
SLOT_FACTOR = 1.5


def auto_clusters(num_rows: int) -> int:
    """Default cluster count: next power of two at or above sqrt(rows)
    (the O(√V·d) operating point), floored so tiny tables still get a
    real two-stage structure."""
    return max(4, next_pow2(math.ceil(math.sqrt(max(1, num_rows)))))


def member_slots(num_rows: int, clusters: int) -> int:
    """Padded member slots per cluster. A FIXED function of the
    engine's ROW CAPACITY (``num_rows`` = vocab + extra rows) and the
    cluster count — never of an actual cluster census — so streaming
    growth and index rebuilds can never change a compiled shape."""
    return max(8, next_pow2(math.ceil(SLOT_FACTOR * num_rows / clusters)))


# ----------------------------------------------------------------------
# Jitted program factories (module-level cache, shapes in the key; all
# array state arrives as traced arguments so rebuilt indexes and
# hot-swapped tables reuse every compiled program)
# ----------------------------------------------------------------------

_PROGRAMS: Dict[tuple, object] = {}


def _program(key, build):
    fn = _PROGRAMS.get(key)
    if fn is None:
        fn = _PROGRAMS[key] = build()
    return fn


def _kmeans_sweep_fn(S: int, C: int, d: int):
    """One spherical k-means iteration over the (S, d) normalized
    sample: blocked assignment GEMMs + segment-sum update, centroids
    re-normalized; empty clusters keep their previous centroid. The
    sample weight vector masks padding rows out of the update."""

    def build():
        def sweep(xn, w, cent):
            xb = xn.reshape(S // ASSIGN_BLOCK, ASSIGN_BLOCK, d)
            wb = w.reshape(S // ASSIGN_BLOCK, ASSIGN_BLOCK)

            def body(carry, xw):
                sums, counts = carry
                x, wt = xw
                a = jnp.argmax(x @ cent.T, axis=1)  # (B,)
                sums = sums + jax.ops.segment_sum(
                    x * wt[:, None], a, num_segments=C
                )
                counts = counts + jax.ops.segment_sum(
                    wt, a, num_segments=C
                )
                return (sums, counts), None

            (sums, counts), _ = jax.lax.scan(
                body,
                (jnp.zeros((C, d), jnp.float32), jnp.zeros((C,), jnp.float32)),
                (xb, wb),
            )
            nrm = jnp.linalg.norm(sums, axis=1, keepdims=True)
            fresh = sums / jnp.where(nrm > 0, nrm, 1.0)
            keep = (counts > 0)[:, None] & (nrm > 0)
            return jnp.where(keep, fresh, cent)

        return jax.jit(sweep)

    return _program(("kmeans_sweep", S, C, d), build)


def _assign_fn(B: int, C: int, d: int):
    """Assign ``B`` table rows (by id) to their best centroid: gather,
    normalize (zero-norm rows score 0 everywhere — their assignment is
    meaningless and the packer skips them), one (B, C) GEMM, argmax."""

    def build():
        def assign(syn0, norms, ids, cent):
            x = syn0[ids].astype(jnp.float32)[:, :d]
            n = norms[ids]
            xn = x * jnp.where(n > 0, 1.0 / jnp.where(n > 0, n, 1.0), 0.0)[
                :, None
            ]
            return jnp.argmax(xn @ cent.T, axis=1).astype(jnp.int32)

        return jax.jit(assign)

    return _program(("ann_assign", B, C, d), build)


def _score_fn(B: int, C: int, d: int):
    """Full (B, C) centroid scores for ``B`` rows — the spill path's
    preference order."""

    def build():
        def score(syn0, norms, ids, cent):
            x = syn0[ids].astype(jnp.float32)[:, :d]
            n = norms[ids]
            xn = x * jnp.where(n > 0, 1.0 / jnp.where(n > 0, n, 1.0), 0.0)[
                :, None
            ]
            return xn @ cent.T

        return jax.jit(score)

    return _program(("ann_score", B, C, d), build)


def _search_fn(Q: int, k: int, nprobe: int, C: int, L: int, d: int):
    """The two-stage query program: coarse top-``nprobe`` over the
    centroid GEMM, then exact masked rerank inside the probed
    clusters' PADDED MEMBER BLOCKS — ``member_rows[pid]`` gathers
    ``nprobe`` contiguous (L, d) blocks per query (whole-slice
    gathers, not per-row ones: XLA CPU scalar-izes a 4096-row gather
    into ~2x the whole rerank's cost, and on TPU a block is one DMA),
    and the scoring is a batched dense (P·L, d) x (d,) contraction —
    the "small dense GEMMs over a learned coarse structure" the issue
    names.

    Masking mirrors the exact path's ``_mask_terms`` contract exactly:
    a slot scores ``dot * inv_norm`` with ``-inf`` wherever it must not
    surface — empty slots and zero-norm rows (``member_invn == 0``) and
    rows at/past the traced ``n_queryable`` bound (freed extra rows
    between index refreshes). ``n_queryable`` being traced means vocab
    growth/shrink never recompiles a warmed program (the PR 2/10
    contract, extended to the approximate path)."""

    def build():
        def search(member_rows, cent, members, invn, q, nq):
            coarse = q @ cent.T  # (Q, C)
            _, pid = jax.lax.top_k(coarse, nprobe)  # (Q, nprobe)
            blocks = member_rows[pid].astype(jnp.float32)
            dots = jnp.matmul(
                blocks.reshape(Q, nprobe * L, d), q[:, :, None]
            )[:, :, 0]  # (Q, P*L)
            cand = members[pid].reshape(Q, nprobe * L)
            inv = invn[pid].reshape(Q, nprobe * L)
            ok = (inv > 0) & (cand < nq)
            scores = dots * inv + jnp.where(ok, 0.0, -jnp.inf)
            val, pos = jax.lax.top_k(scores, k)
            return val, jnp.take_along_axis(cand, pos, axis=1)

        return jax.jit(search)

    return _program(("ann_search", Q, k, nprobe, C, L, d), build)


def _gather_blocks_fn(C: int, L: int):
    """Materialize the (C, L, d) member-block layout from the table —
    the one big per-row gather, paid ONCE per build/refresh, off the
    request path."""

    def build():
        def gather(syn0, members):
            return jnp.take(syn0, members.reshape(-1), axis=0).reshape(
                C, L, syn0.shape[1]
            )

        return jax.jit(gather)

    return _program(("ann_gather_blocks", C, L), build)


def _refresh_cluster_fn(L: int):
    """Refresh ONE cluster's member block from the live table after an
    incremental layout edit (promotion burst / free back-fill): a
    fixed-shape (L, d) gather + one block set, cluster id traced."""

    def build():
        def refresh(member_rows, syn0, row_ids, c):
            return member_rows.at[c].set(
                syn0[row_ids].astype(member_rows.dtype)
            )

        return jax.jit(refresh)

    return _program(("ann_refresh_cluster", L), build)


# ----------------------------------------------------------------------
# The index value
# ----------------------------------------------------------------------


@dataclass
class AnnIndex:
    """A built coarse index over one table generation.

    Device state (replicated): ``centroids`` (C, dp) f32 row-normalized,
    ``members`` (C, L) int32 global row ids (0 in empty slots),
    ``member_invn`` (C, L) f32 reciprocal row norms (0 marks an empty
    slot OR a zero-norm row — either way the slot can never surface),
    and ``member_rows`` (C, L, dp) in TABLE dtype — the padded
    cluster-member block layout the rerank scores against (a
    ``SLOT_FACTOR``-sized copy of its generation's table, which is
    also what makes a served response single-generation by
    construction: the index never reads the live table at query time).

    Host masters mirror the member layout so incremental updates edit
    in place and re-stage only the small id/norm arrays plus the
    touched clusters' blocks; per-row ``cluster_of``/``slot_of`` make
    removal O(1) per touched row.
    """

    clusters: int
    slots: int
    dim: int
    centroids: jax.Array
    members: jax.Array
    member_invn: jax.Array
    member_rows: jax.Array
    members_np: np.ndarray
    invn_np: np.ndarray
    fill: np.ndarray  # (C,) live members per cluster
    cluster_of: np.ndarray  # (num_rows,) int32, -1 = not indexed
    slot_of: np.ndarray  # (num_rows,) int32
    table_version: int
    build_seconds: float
    built_rows: int  # queryable rows at build time
    sampled_rows: int
    spilled_rows: int
    iters: int
    updated_rows: int = 0  # incrementally re-bucketed since build
    _sharding: object = field(default=None, repr=False)

    def stats(self) -> dict:
        """Host-side summary for the serving ``index_*`` gauge family
        (every field is already a host scalar)."""
        return {
            "clusters": self.clusters,
            "member_slots": self.slots,
            "build_seconds": round(self.build_seconds, 3),
            "built_rows": self.built_rows,
            "sampled_rows": self.sampled_rows,
            "spilled_rows": self.spilled_rows,
            "updated_rows": self.updated_rows,
            "kmeans_iters": self.iters,
            "table_version": self.table_version,
        }

    def _restage(self) -> None:
        """Push the edited host member masters back to device (same
        shapes — warmed search programs are reused as-is)."""
        self.members = jax.device_put(
            jnp.asarray(self.members_np), self._sharding
        )
        self.member_invn = jax.device_put(
            jnp.asarray(self.invn_np), self._sharding
        )


# ----------------------------------------------------------------------
# Build
# ----------------------------------------------------------------------


def _pack_members(
    assign: np.ndarray,
    inv: np.ndarray,
    live_ids: np.ndarray,
    C: int,
    L: int,
    pref_scores,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """Pack assigned rows into the fixed (C, L) slot layout, spilling
    the overflow of oversized clusters to their next-best cluster with
    space (``pref_scores(ids) -> (n, C)`` supplies preference rows for
    exactly the spilled ids). Returns the host masters + spill count.

    The non-spill majority places vectorized (stable argsort + rank
    within cluster) — a per-row Python loop here runs per index build
    AND per hot-swap staging, which at multi-million-row vocabs would
    stall generation adoption by itself; only the rare spill tail pays
    per-row work."""
    num_rows_bound = int(live_ids.max()) + 1 if live_ids.size else 1
    members = np.zeros((C, L), np.int32)
    invn = np.zeros((C, L), np.float32)
    cluster_of = np.full(num_rows_bound, -1, np.int32)
    slot_of = np.zeros(num_rows_bound, np.int32)

    order = np.argsort(assign, kind="stable")
    c_o = assign[order].astype(np.int64)
    counts = np.bincount(c_o, minlength=C)
    starts = np.zeros(C + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    # Rank of each row inside its cluster (stable order): rows ranked
    # past L are the spill tail.
    ranks = np.arange(order.size, dtype=np.int64) - starts[c_o]
    fit = ranks < L
    rows_o = live_ids[order].astype(np.int64)
    members[c_o[fit], ranks[fit]] = rows_o[fit]
    invn[c_o[fit], ranks[fit]] = inv[order][fit]
    cluster_of[rows_o[fit]] = c_o[fit]
    slot_of[rows_o[fit]] = ranks[fit]
    fill = np.minimum(counts, L)
    spilled = order[~fit]
    if spilled.size:
        sp = np.asarray(spilled, np.int64)
        scores = pref_scores(live_ids[sp])  # (n_spill, C)
        pref = np.argsort(-scores, axis=1)
        for row, pos in enumerate(sp):
            rid = int(live_ids[pos])
            placed = False
            for c in pref[row]:
                c = int(c)
                if fill[c] < L:
                    s = int(fill[c])
                    members[c, s] = rid
                    invn[c, s] = inv[pos]
                    cluster_of[rid] = c
                    slot_of[rid] = s
                    fill[c] += 1
                    placed = True
                    break
            # Total capacity C*L >= SLOT_FACTOR * rows > rows, so some
            # cluster always has space.
            assert placed, "ANN member capacity exhausted"
    return members, invn, fill, cluster_of, slot_of, len(spilled)


def build(
    syn0,
    norms,
    queryable: int,
    *,
    clusters: Optional[int] = None,
    nprobe_hint: int = 8,
    iters: int = 6,
    sample: int = 65536,
    seed: int = 0,
    table_version: int = 0,
    num_rows: Optional[int] = None,
    sharding=None,
) -> AnnIndex:
    """Train centroids on-device from ``syn0`` and pack the member
    layout. ``syn0``/``norms`` may be the LIVE tables or a STAGED
    generation's — nothing here reads or writes engine state, which is
    what makes the index swap-native. ``num_rows`` fixes the slot
    geometry (defaults to ``queryable``; pass the engine's full row
    capacity so streaming growth keeps shapes stable).

    Host syncs below (sampling ids, the assignment readback, member
    packing) all run OFF the request path by contract: build/refresh
    happens at boot or on the staging thread of a hot-swap.
    """
    t0 = time.perf_counter()
    V = int(queryable)
    capacity = int(num_rows if num_rows is not None else V)
    C = int(clusters) if clusters else auto_clusters(capacity)
    L = member_slots(capacity, C)
    dp = int(syn0.shape[1])
    d = dp  # centroids live at padded width; query vectors arrive padded

    norms_np = np.asarray(norms, np.float32)[:V]
    live_ids = np.flatnonzero(norms_np > 0).astype(np.int32)
    inv_all = np.zeros(V, np.float32)
    inv_all[live_ids] = 1.0 / norms_np[live_ids]

    rng = np.random.default_rng(seed)
    S_raw = min(int(sample), live_ids.size)
    if live_ids.size and S_raw:
        sample_ids = (
            live_ids
            if S_raw == live_ids.size
            else rng.choice(live_ids, S_raw, replace=False).astype(np.int32)
        )
    else:
        sample_ids = np.zeros(1, np.int32)
        S_raw = 0
    S = max(ASSIGN_BLOCK, next_pow2(max(1, S_raw)))
    ids_pad = np.zeros(S, np.int32)
    ids_pad[:S_raw] = sample_ids[:S_raw]
    w = np.zeros(S, np.float32)
    w[:S_raw] = 1.0

    # Normalized sample matrix, built by one device gather (same shapes
    # as the assignment sweeps), then the fixed-iteration sweep loop —
    # one compiled program however many iterations run.
    xg = np.asarray(
        syn0[jnp.asarray(ids_pad)].astype(jnp.float32)
    )[:, :d]
    xn = xg * (inv_all[ids_pad] * w)[:, None]

    # Deterministic init: centroids from evenly strided sample rows
    # (already normalized); degenerate tables fall back to unit e0.
    if S_raw >= C:
        init = xn[np.linspace(0, S_raw - 1, C).astype(np.int64)]
    else:
        init = np.zeros((C, d), np.float32)
        init[:S_raw] = xn[:S_raw]
    zero = np.linalg.norm(init, axis=1) == 0
    if zero.any():
        fallback = np.zeros(d, np.float32)
        fallback[0] = 1.0
        init[zero] = fallback

    sweep = _kmeans_sweep_fn(S, C, d)
    xn_dev = jnp.asarray(xn)
    w_dev = jnp.asarray(w)
    cent = jnp.asarray(init)
    for _ in range(max(1, int(iters))):
        cent = sweep(xn_dev, w_dev, cent)

    # Full-table assignment: every live row, in fixed ASSIGN_BLOCK
    # chunks (compile-once), argmax readback per chunk.
    afn = _assign_fn(ASSIGN_BLOCK, C, d)
    assign = np.zeros(live_ids.size, np.int32)
    for s in range(0, live_ids.size, ASSIGN_BLOCK):
        chunk = live_ids[s : s + ASSIGN_BLOCK]
        n = chunk.shape[0]
        if n < ASSIGN_BLOCK:
            chunk = np.concatenate(
                [chunk, np.zeros(ASSIGN_BLOCK - n, np.int32)]
            )
        out = np.asarray(
            afn(syn0, norms, jnp.asarray(chunk), cent)
        )
        assign[s : s + n] = out[:n]

    sfn = _score_fn(INCREMENTAL_BLOCK, C, d)

    def pref_scores(ids: np.ndarray) -> np.ndarray:
        out = np.zeros((ids.size, C), np.float32)
        for s in range(0, ids.size, INCREMENTAL_BLOCK):
            chunk = ids[s : s + INCREMENTAL_BLOCK].astype(np.int32)
            n = chunk.shape[0]
            if n < INCREMENTAL_BLOCK:
                chunk = np.concatenate(
                    [chunk, np.zeros(INCREMENTAL_BLOCK - n, np.int32)]
                )
            out[s : s + n] = np.asarray(
                sfn(syn0, norms, jnp.asarray(chunk), cent)
            )[:n]
        return out

    inv_live = inv_all[live_ids]
    members, invn, fill, cluster_of, slot_of, n_spill = _pack_members(
        assign, inv_live, live_ids, C, L, pref_scores
    )
    # Per-row maps sized to the full capacity so later promotions index
    # directly.
    cap = max(capacity, cluster_of.shape[0])
    cof = np.full(cap, -1, np.int32)
    sof = np.zeros(cap, np.int32)
    cof[: cluster_of.shape[0]] = cluster_of
    sof[: slot_of.shape[0]] = slot_of

    idx = AnnIndex(
        clusters=C,
        slots=L,
        dim=d,
        centroids=jax.device_put(cent, sharding),
        members=None,
        member_invn=None,
        member_rows=None,
        members_np=members,
        invn_np=invn,
        fill=fill,
        cluster_of=cof,
        slot_of=sof,
        table_version=int(table_version),
        build_seconds=0.0,
        built_rows=V,
        sampled_rows=int(S_raw),
        spilled_rows=int(n_spill),
        iters=int(iters),
        _sharding=sharding,
    )
    idx._restage()
    # The block layout: one big gather from the SOURCE table (live or
    # staged), off the request path by contract.
    idx.member_rows = _gather_blocks_fn(C, L)(syn0, idx.members)
    idx.build_seconds = time.perf_counter() - t0
    return idx


# ----------------------------------------------------------------------
# Incremental maintenance (streaming promotions / frees / row writes)
# ----------------------------------------------------------------------


def _refresh_clusters(index: AnnIndex, syn0, clusters) -> None:
    """Re-materialize the member blocks of ONLY the touched clusters
    from the table — the incremental path never rebuilds the (C, L, d)
    layout wholesale."""
    if not clusters:
        return
    fn = _refresh_cluster_fn(index.slots)
    rows = index.member_rows
    for c in sorted(clusters):
        rows = fn(
            rows, syn0, jnp.asarray(index.members_np[c]), jnp.int32(c)
        )
    index.member_rows = rows


def add_rows(index: AnnIndex, syn0, norms, ids: Sequence[int]) -> int:
    """Bucket newly written/promoted rows into the live layout — ONLY
    these rows move; everything else (centroids, every other member
    slot, every untouched cluster block) is untouched. Rows are
    assigned to their best centroid with space (preference order from
    one small fixed-shape score GEMM per INCREMENTAL_BLOCK chunk).
    Zero-norm rows are skipped (they can never surface). Returns the
    number of rows actually inserted."""
    ids = np.asarray(list(ids), np.int64)
    if ids.size == 0:
        return 0
    norms_host = np.asarray(norms, np.float32)
    sfn = _score_fn(INCREMENTAL_BLOCK, index.clusters, index.dim)
    inserted = 0
    touched: set = set()
    for s in range(0, ids.size, INCREMENTAL_BLOCK):
        chunk = ids[s : s + INCREMENTAL_BLOCK]
        n = chunk.shape[0]
        padded = np.zeros(INCREMENTAL_BLOCK, np.int32)
        padded[:n] = chunk
        scores = np.asarray(
            sfn(syn0, norms, jnp.asarray(padded), index.centroids)
        )[:n]
        pref = np.argsort(-scores, axis=1)
        for row, rid in enumerate(chunk):
            rid = int(rid)
            if rid >= index.cluster_of.shape[0]:
                continue  # beyond the indexed row capacity (bucket rows)
            if index.cluster_of[rid] >= 0:
                _drop_row(index, rid, touched)
            nr = norms_host[rid]
            if nr <= 0:
                continue
            for c in pref[row]:
                c = int(c)
                if index.fill[c] < index.slots:
                    slot = int(index.fill[c])
                    index.members_np[c, slot] = rid
                    index.invn_np[c, slot] = 1.0 / nr
                    index.cluster_of[rid] = c
                    index.slot_of[rid] = slot
                    index.fill[c] += 1
                    inserted += 1
                    touched.add(c)
                    break
    if inserted or ids.size:
        index.updated_rows += int(ids.size)
        index._restage()
        _refresh_clusters(index, syn0, touched)
    return inserted


def _drop_row(index: AnnIndex, rid: int, touched: set) -> None:
    """Remove one row from its slot, back-filling with the cluster's
    last member so the live prefix stays dense."""
    c = int(index.cluster_of[rid])
    if c < 0:
        return
    s = int(index.slot_of[rid])
    last = int(index.fill[c]) - 1
    if s != last:
        mover = int(index.members_np[c, last])
        index.members_np[c, s] = mover
        index.invn_np[c, s] = index.invn_np[c, last]
        index.slot_of[mover] = s
    index.members_np[c, last] = 0
    index.invn_np[c, last] = 0.0
    index.fill[c] = last
    index.cluster_of[rid] = -1
    touched.add(c)


def remove_rows(index: AnnIndex, syn0, ids: Sequence[int]) -> int:
    """Drop rows from the layout (freed extra rows). The traced
    ``n_queryable`` bound already hides them from searches the moment
    the engine shrinks; this keeps the layout dense and the slots
    reusable (the back-filled slots' blocks refresh from ``syn0``).
    Returns the number of rows removed."""
    removed = 0
    touched: set = set()
    for rid in ids:
        rid = int(rid)
        if 0 <= rid < index.cluster_of.shape[0] and \
                index.cluster_of[rid] >= 0:
            _drop_row(index, rid, touched)
            removed += 1
    if removed:
        index.updated_rows += removed
        index._restage()
        _refresh_clusters(index, syn0, touched)
    return removed


def update_rows(index: AnnIndex, syn0, norms, ids: Sequence[int]) -> int:
    """Re-bucket rows whose VALUES changed (write_rows): drop + re-add
    with fresh norms/assignments. Touched rows only."""
    return add_rows(index, syn0, norms, ids)
