"""On-device skip-gram batch assembly from a device-resident corpus.

The host pipeline (corpus/batching.py) streams fixed-shape minibatches and
re-uploads ~60 bytes/center-position per step. This module is the
TPU-native alternative: the flat encoded corpus (``ids int32[N]``,
``offsets int32[S+1]`` — corpus/vocab.encode_file's representation) is
uploaded to HBM ONCE (~4 bytes per kept word), and every minibatch is
assembled *inside* the jitted train scan from nothing but a position
counter and the step's PRNG key. Host->device traffic per dispatch drops
from O(steps_per_call * batch * context) to a handful of scalars.

Semantics mirror the reference's windowing exactly as restated in
corpus/batching.py (mllib:381-390): for center position ``i`` draw
``b ~ U[0, window)`` and take context positions ``[max(0, i-b),
min(i+b, len)) \\ {i}`` — the half-open upper bound inherited from
Scala's ``until``, hence lanes spanning offsets ``[-(W-1), W-2]`` and
``context_width(W) = 2W-3``. Sentence bounds come from ``offsets`` via
``searchsorted``. Without subsampling the center-position stream is the
corpus in order — exactly the host batcher's packing — so the device
path is batch-for-batch identical to the Python path modulo the window
shrink RNG stream (device threefry vs host PCG64; the host native/C++
pass already diverges from Python the same way).

Frequency subsampling *compacts* sentences before windowing (it changes
neighbor distances), which is a data-dependent reshape the static-shape
scan cannot express cheaply; callers with ``subsample_ratio > 0`` keep
the host pipeline (models/word2vec.py routes accordingly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from glint_word2vec_tpu.corpus.batching import window_offsets

#: Domain-separation constant for the window-shrink draws ("wind").
WINDOW_FOLD = 0x77696E64


def device_window_batch(
    ids: jax.Array,  # (N,) int32 flat corpus
    offsets: jax.Array,  # (S+1,) int32 sentence offsets
    positions: jax.Array,  # (B,) int32 center positions (may exceed N: masked)
    rows: jax.Array,  # (B,) int32 GLOBAL batch-row indices (key the draws)
    key: jax.Array,
    window: int,
):
    """Assemble one (centers, contexts, mask) minibatch on device.

    ``positions`` beyond the corpus end yield fully-masked rows (the
    epoch-tail padding the host batcher expresses with zero-mask rows).
    The shrink draw for row ``i`` depends only on ``(key, rows[i])`` —
    the per-GLOBAL-row keying of ops/sampling.sample_negatives_per_row —
    so a data rank holding global rows [r0, r0+Bl) draws exactly what a
    single-rank run draws for those rows while doing only O(local rows)
    work (no global-batch over-draw).
    """
    N = ids.shape[0]
    W = int(window)

    # Both bounds: upload_corpus permits N up to 2**31-1, so a tail
    # group's positions can overflow int32 and wrap negative — without
    # the >= 0 check a wrapped position would clip to 0 and train real
    # updates on sentence-0 windows instead of masking out.
    in_corpus = (positions >= 0) & (positions < N)
    p = jnp.clip(positions, 0, max(N - 1, 0))
    sent = jnp.searchsorted(offsets, p, side="right") - 1
    start = offsets[sent]
    end = offsets[sent + 1]

    base = jax.random.fold_in(key, WINDOW_FOLD)
    keys = jax.vmap(lambda r: jax.random.fold_in(base, r))(rows)
    b = jax.vmap(
        lambda k: jax.random.randint(k, (), 0, W, dtype=jnp.int32)
    )(keys)
    offs = jnp.asarray(window_offsets(W), dtype=jnp.int32)  # (C,) static
    cpos = p[:, None] + offs[None, :]
    valid = (
        (offs[None, :] >= -b[:, None])
        & (offs[None, :] <= b[:, None] - 1)
        & (cpos >= start[:, None])
        & (cpos < end[:, None])
        & in_corpus[:, None]
    )
    centers = jnp.where(in_corpus, ids[p], 0).astype(jnp.int32)
    contexts = jnp.where(valid, ids[jnp.clip(cpos, 0, max(N - 1, 0))], 0)
    return centers, contexts.astype(jnp.int32), valid.astype(jnp.float32)


def corpus_words_done(offsets: np.ndarray, end_position: int) -> int:
    """Host-side words_done after consuming center positions [0, end).

    Matches the host batcher's accounting (corpus/batching.py): a
    sentence's full word count is added as soon as ANY of its positions
    is consumed, so the value after a batch ending inside sentence ``j``
    is ``offsets[j+1]``.
    """
    if end_position <= 0:
        return 0
    end_position = min(int(end_position), int(offsets[-1]))
    j = int(np.searchsorted(offsets, end_position - 1, side="right")) - 1
    return int(offsets[j + 1])
