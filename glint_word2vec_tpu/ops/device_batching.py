"""On-device skip-gram batch assembly from a device-resident corpus.

The host pipeline (corpus/batching.py) streams fixed-shape minibatches and
re-uploads ~60 bytes/center-position per step. This module is the
TPU-native alternative: the flat encoded corpus (``ids int32[N]``,
``offsets int32[S+1]`` — corpus/vocab.encode_file's representation) is
uploaded to HBM ONCE (~4 bytes per kept word), and every minibatch is
assembled *inside* the jitted train scan from nothing but a position
counter and the step's PRNG key. Host->device traffic per dispatch drops
from O(steps_per_call * batch * context) to a handful of scalars.

Semantics mirror the reference's windowing exactly as restated in
corpus/batching.py (mllib:381-390): for center position ``i`` draw
``b ~ U[0, window)`` and take context positions ``[max(0, i-b),
min(i+b, len)) \\ {i}`` — the half-open upper bound inherited from
Scala's ``until``, hence lanes spanning offsets ``[-(W-1), W-2]`` and
``context_width(W) = 2W-3``. Sentence bounds come from ``offsets`` via
``searchsorted``. Without subsampling the center-position stream is the
corpus in order — exactly the host batcher's packing — so the device
path is batch-for-batch identical to the Python path modulo the window
shrink RNG stream (device threefry vs host PCG64; the host native/C++
pass already diverges from Python the same way).

Frequency subsampling *compacts* sentences before windowing (it changes
neighbor distances, exactly the reference's per-iteration pass,
mllib:371-390). That compaction now ALSO runs on device: once per epoch
:func:`subsample_compact` draws the keep mask (Bernoulli with
``keep_prob[ids[t]]``, keyed ``fold_in(epoch_key, position)`` so the
draws are mesh-invariant by construction), prefix-sums it, and
scatter-compacts ``(ids, offsets)`` into same-shape device buffers
``(ids_c, offsets_c)`` plus a traced element count ``n_kept``.
:func:`device_window_batch` then runs unchanged over the compacted
arrays with ``n_kept`` as its (traced) corpus-end bound — so
``subsample_ratio > 0`` keeps the scalars-only dispatch path instead of
falling back to the host batcher (models/word2vec.py routes the device
path for both settings).

The shrink draw plus sentence clipping leave only ~0.43 of the ``(B, C)``
context grid live (``E[max(2b-1, 0)]/C`` for ``b ~ U[0, W)``), so the
grid-shaped step burns >2x the FLOPs per useful pair. The PACKED dispatch
mode (:func:`pack_window_pairs`, ``set_batch_packing("dense")``) fixes
that the pSGNScc way (arxiv 1604.04661: restructure the batch so the
matrix work is dense): windows are assembled over an oversized candidate
span, the valid (center, context) pairs are prefix-sum scatter-compacted
into a fixed-shape dense pair list, and the step runs the rank-1 SGNS
update over pairs — effective mask density ~0.43 -> >=0.95 on the corpus
path at the same dispatched step cost.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from glint_word2vec_tpu.corpus.batching import window_offsets

#: Domain-separation constant for the window-shrink draws ("wind").
WINDOW_FOLD = 0x77696E64

#: Domain-separation constant for the per-epoch subsample draws ("subs"):
#: folded into the epoch key before the per-position fold, so the
#: subsample stream can never collide with the window/negative streams
#: (those fold WINDOW_FOLD or a global row index < 2**30 instead).
SUBSAMPLE_FOLD = 0x73756273


def device_window_batch(
    ids: jax.Array,  # (N,) int32 flat corpus
    offsets: jax.Array,  # (S+1,) int32 sentence offsets
    positions: jax.Array,  # (B,) int32 center positions (may exceed N: masked)
    rows: jax.Array,  # (B,) int32 GLOBAL batch-row indices (key the draws)
    key: jax.Array,
    window: int,
    n_valid=None,  # traced int32 scalar corpus-end bound (None = ids.shape[0])
):
    """Assemble one (centers, contexts, mask) minibatch on device.

    ``positions`` beyond the corpus end yield fully-masked rows (the
    epoch-tail padding the host batcher expresses with zero-mask rows).
    The shrink draw for row ``i`` depends only on ``(key, rows[i])`` —
    the per-GLOBAL-row keying of ops/sampling.sample_negatives_per_row —
    so a data rank holding global rows [r0, r0+Bl) draws exactly what a
    single-rank run draws for those rows while doing only O(local rows)
    work (no global-batch over-draw).

    ``n_valid`` is the corpus-end bound as a *traced* scalar — the
    subsampled path passes the epoch's ``n_kept`` (compacted buffers
    keep the static shape N, only a prefix is live). None (the
    un-subsampled path) means the full static extent. Context validity
    needs no extra bound: compacted sentence offsets never exceed
    ``n_kept``, so the sentence-end check already excludes the dead tail.
    """
    N = ids.shape[0]
    W = int(window)
    if n_valid is None:
        n_valid = N

    # Both bounds: upload_corpus permits N up to 2**31-1, so a tail
    # group's positions can overflow int32 and wrap negative — without
    # the >= 0 check a wrapped position would clip to 0 and train real
    # updates on sentence-0 windows instead of masking out.
    in_corpus = (positions >= 0) & (positions < n_valid)
    p = jnp.clip(positions, 0, max(N - 1, 0))
    sent = jnp.searchsorted(offsets, p, side="right") - 1
    start = offsets[sent]
    end = offsets[sent + 1]

    base = jax.random.fold_in(key, WINDOW_FOLD)
    keys = jax.vmap(lambda r: jax.random.fold_in(base, r))(rows)
    b = jax.vmap(
        lambda k: jax.random.randint(k, (), 0, W, dtype=jnp.int32)
    )(keys)
    offs = jnp.asarray(window_offsets(W), dtype=jnp.int32)  # (C,) static
    cpos = p[:, None] + offs[None, :]
    valid = (
        (offs[None, :] >= -b[:, None])
        & (offs[None, :] <= b[:, None] - 1)
        & (cpos >= start[:, None])
        & (cpos < end[:, None])
        & in_corpus[:, None]
    )
    centers = jnp.where(in_corpus, ids[p], 0).astype(jnp.int32)
    contexts = jnp.where(valid, ids[jnp.clip(cpos, 0, max(N - 1, 0))], 0)
    return centers, contexts.astype(jnp.int32), valid.astype(jnp.float32)


def subsample_keep_mask(
    ids: jax.Array, keep_prob: jax.Array, epoch_key: jax.Array
) -> jax.Array:
    """Per-position Bernoulli keep mask for frequency subsampling.

    Position ``t`` is kept iff ``u_t <= keep_prob[ids[t]]`` with
    ``u_t ~ U[0, 1)`` — the host rule (corpus/batching.subsample_sentence)
    on a device RNG stream. Each draw is keyed
    ``fold_in(fold_in(epoch_key, SUBSAMPLE_FOLD), t)``: purely elementwise
    in the position, so the values cannot depend on how GSPMD partitions
    the computation — mesh-invariant by construction (a bulk
    ``uniform(key, (N,))`` draw is NOT under the legacy non-partitionable
    threefry lowering).
    """
    N = ids.shape[0]
    base = jax.random.fold_in(epoch_key, SUBSAMPLE_FOLD)
    u = jax.vmap(
        lambda t: jax.random.uniform(jax.random.fold_in(base, t), ())
    )(jnp.arange(N, dtype=jnp.uint32))
    return u <= keep_prob[ids]


def subsample_compact(
    ids: jax.Array,  # (N,) int32 flat corpus
    offsets: jax.Array,  # (S+1,) int32 sentence offsets
    keep_prob: jax.Array,  # (V,) float32 per-word keep probability
    epoch_key: jax.Array,
):
    """One epoch's subsample-and-compact pass, entirely on device.

    Returns ``(ids_c, offsets_c, n_kept)``: the kept tokens compacted to
    the front of a same-shape (N,) buffer (tail zeros are dead — bounded
    off by ``n_kept`` and the compacted sentence offsets), the per-
    sentence offsets remapped into compacted coordinates (a sentence
    subsampled to nothing becomes an empty span, which ``searchsorted``
    in :func:`device_window_batch` skips naturally), and the traced kept
    count. Compaction happens BEFORE windowing — the reference's
    semantics (mllib:371-390): dropping a word shortens the distances
    between its surviving neighbors.

    The pass is integer-exact (elementwise draws, int32 prefix sum,
    deterministic scatter), so its output is bitwise identical on every
    mesh shape. HBM cost: one extra int32 buffer per corpus word plus
    the transient prefix sums (~12 bytes/word peak together with the
    flat corpus; models/word2vec._device_corpus_eligible budgets this).
    """
    N = ids.shape[0]
    keep = subsample_keep_mask(ids, keep_prob, epoch_key)
    k32 = keep.astype(jnp.int32)
    incl = jnp.cumsum(k32)  # inclusive prefix: kept in [0, t]
    n_kept = incl[-1] if N else jnp.int32(0)
    dest = incl - k32  # exclusive prefix = compacted destination
    scatter_idx = jnp.where(keep, dest, N)  # dropped tokens land out of range
    ids_c = jnp.zeros(N, jnp.int32).at[scatter_idx].set(ids, mode="drop")
    kept_before = jnp.concatenate([jnp.zeros(1, jnp.int32), incl])
    offsets_c = kept_before[offsets].astype(jnp.int32)
    return ids_c, offsets_c, n_kept


def grid_window_shrink(
    base_key: jax.Array,
    positions: jax.Array,  # (S,) int32 center positions, >= 0
    grid_batch: int,  # B of the grid scan being reproduced
    grid_step0,  # traced uint32: grid step counter at position 0
    window: int,
) -> jax.Array:
    """The window-shrink draw the GRID corpus scan makes for each position.

    In the grid scan (parallel/engine.make_corpus_scan) position ``p``
    lands in grid step ``grid_step0 + p // B`` at batch row ``p % B``, and
    its shrink is drawn from
    ``fold_in(fold_in(fold_in(base_key, step), WINDOW_FOLD), row)``. The
    packed scan reproduces exactly those draws — a deterministic function
    of the global position — so the packed pair stream is the *same
    multiset of valid (center, context) pairs* the grid scan trains on
    (the parity contract tests/test_packed.py pins down), and is
    mesh-invariant for free (no dependence on where packing boundaries or
    data ranks fall).
    """
    W = int(window)
    gi = (positions // jnp.int32(grid_batch)).astype(jnp.uint32)
    row = (positions % jnp.int32(grid_batch)).astype(jnp.uint32)

    def draw(g, r):
        k = jax.random.fold_in(base_key, grid_step0 + g)
        k = jax.random.fold_in(k, WINDOW_FOLD)
        k = jax.random.fold_in(k, r)
        return jax.random.randint(k, (), 0, W, dtype=jnp.int32)

    return jax.vmap(draw)(gi, row)


def pack_window_pairs(
    ids: jax.Array,  # (N,) int32 flat corpus (active view)
    offsets: jax.Array,  # (S+1,) int32 sentence offsets (active view)
    pos,  # traced int32 scalar: first unconsumed center position
    base_key: jax.Array,
    grid_step0,  # traced uint32 (see grid_window_shrink)
    *,
    window: int,
    span: int,  # candidate center positions examined per step
    pair_batch: int,  # P: dense pair slots per step
    grid_batch: int,  # B of the grid scan whose draws are reproduced
    n_valid,  # traced int32 corpus-end bound
):
    """Assemble one DENSE (center, context) pair batch on device.

    Windows are built over the oversized candidate span
    ``[pos, pos + span)`` exactly as :func:`device_window_batch` builds
    them (shrink draw + sentence bounds), then the valid pairs are
    prefix-sum scatter-compacted to the front of a fixed ``(P,)`` pair
    list — the same compaction machinery :func:`subsample_compact` proved
    out, applied to pair lanes instead of tokens. Only *whole* center
    positions are consumed: ``n_cons`` is the largest prefix of the span
    whose cumulative valid-pair count fits in ``P``, so the unconsumed
    remainder is carried simply as the position counter (positions past
    ``pos + n_cons`` are re-assembled next step — the draws are
    position-deterministic, so recomputation is exact) and no partial
    position ever splits across steps.

    Returns ``(pcenters (P,), pcontexts (P,), pmask (P,), n_cons (),
    n_pairs ())``: the dense pair list in position-major, lane-minor
    order (slots past ``n_pairs`` are index-0 / mask-0 padding),
    the consumed-position advance, and the live pair count. Guarantees
    ``n_cons >= 1`` whenever ``P >= context_width(window)`` (a single
    position yields at most C pairs), so the scan always makes progress;
    positions at or past ``n_valid`` contribute zero pairs but are still
    consumed (the epoch tail drains in ``span``-sized strides).
    """
    N = ids.shape[0]
    W = int(window)
    S = int(span)
    P = int(pair_batch)
    offs = jnp.asarray(window_offsets(W), dtype=jnp.int32)  # (C,) static
    C = offs.shape[0]
    if P < C:
        raise ValueError(f"pair_batch ({P}) must be >= context lanes ({C})")

    positions = pos + jnp.arange(S, dtype=jnp.int32)
    in_corpus = (positions >= 0) & (positions < n_valid)
    p = jnp.clip(positions, 0, max(N - 1, 0))
    sent = jnp.searchsorted(offsets, p, side="right") - 1
    start = offsets[sent]
    end = offsets[sent + 1]
    b = grid_window_shrink(base_key, positions, grid_batch, grid_step0, W)
    cpos = p[:, None] + offs[None, :]
    valid = (
        (offs[None, :] >= -b[:, None])
        & (offs[None, :] <= b[:, None] - 1)
        & (cpos >= start[:, None])
        & (cpos < end[:, None])
        & in_corpus[:, None]
    )  # (S, C)
    centers = jnp.where(in_corpus, ids[p], 0).astype(jnp.int32)
    contexts = jnp.where(
        valid, ids[jnp.clip(cpos, 0, max(N - 1, 0))], 0
    ).astype(jnp.int32)

    # Whole-position consumption: take the longest span prefix whose
    # cumulative pair count fits in P (cum is non-decreasing, so the
    # count of cum <= P IS that prefix length).
    v = valid.sum(axis=1).astype(jnp.int32)  # (S,)
    cum = jnp.cumsum(v)
    n_cons = jnp.sum((cum <= P).astype(jnp.int32))
    consumed = jnp.arange(S, dtype=jnp.int32) < n_cons

    take = (valid & consumed[:, None]).reshape(-1).astype(jnp.int32)
    incl = jnp.cumsum(take)
    n_pairs = incl[-1]  # == cum[n_cons - 1] <= P by construction
    dest = incl - take
    scatter_idx = jnp.where(take > 0, dest, P)  # dropped lanes out of range
    pcenters = (
        jnp.zeros(P, jnp.int32)
        .at[scatter_idx]
        .set(jnp.repeat(centers, C), mode="drop")
    )
    pcontexts = (
        jnp.zeros(P, jnp.int32)
        .at[scatter_idx]
        .set(contexts.reshape(-1), mode="drop")
    )
    pmask = (jnp.arange(P, dtype=jnp.int32) < n_pairs).astype(jnp.float32)
    return pcenters, pcontexts, pmask, n_cons, n_pairs


def device_words_done(
    offsets: jax.Array,  # (S+1,) int32 ORIGINAL sentence offsets
    offsets_c: jax.Array,  # (S+1,) int32 active (possibly compacted) offsets
    end_position,  # traced int32: consumed center positions [0, end)
    n_valid,  # traced int32: live extent of the active position stream
) -> jax.Array:
    """Traced restatement of :func:`corpus_words_done_compacted` — the
    pre-subsampling words_done rule, evaluated inside the jitted packed
    scan so the LR anneal can follow the data-dependent position advance
    without a host round-trip. For the un-subsampled stream pass the
    original offsets as both arguments (then this equals
    :func:`corpus_words_done`). Bit-for-bit the host rule: the parity
    test drives both over every prefix."""
    j = jnp.searchsorted(offsets_c, end_position - 1, side="right") - 1
    done = offsets[jnp.clip(j + 1, 0, offsets.shape[0] - 1)]
    done = jnp.where(end_position >= n_valid, offsets[-1], done)
    return jnp.where(end_position <= 0, 0, done).astype(jnp.int32)


def corpus_words_done(offsets: np.ndarray, end_position: int) -> int:
    """Host-side words_done after consuming center positions [0, end).

    Matches the host batcher's accounting (corpus/batching.py): a
    sentence's full word count is added as soon as ANY of its positions
    is consumed, so the value after a batch ending inside sentence ``j``
    is ``offsets[j+1]``.
    """
    if end_position <= 0:
        return 0
    end_position = min(int(end_position), int(offsets[-1]))
    j = int(np.searchsorted(offsets, end_position - 1, side="right")) - 1
    return int(offsets[j + 1])


def corpus_words_done_compacted(
    offsets: np.ndarray,  # (S+1,) ORIGINAL sentence offsets
    offsets_c: np.ndarray,  # (S+1,) compacted sentence offsets
    end_position: int,  # consumed compacted center positions [0, end)
    n_kept: int,
) -> int:
    """Host-side words_done over the epoch's COMPACTED position stream.

    Same convention as :func:`corpus_words_done` — a sentence's FULL
    pre-subsampling word count is credited as soon as any of its kept
    positions is consumed (the host batcher counts pre-subsampling words
    so the LR anneal never stalls; corpus/batching.SkipGramBatcher) —
    looked up through the compacted offsets. Consuming the whole
    compacted stream credits the whole corpus: the host batcher consumes
    every sentence by then, including ones subsampling emptied.
    """
    if end_position >= n_kept:
        return int(offsets[-1])
    if end_position <= 0:
        return 0
    # side="right" over possibly-repeated compacted offsets: lands past
    # every emptied sentence preceding the one owning position end-1.
    j = int(np.searchsorted(offsets_c, end_position - 1, side="right")) - 1
    return int(offsets[j + 1])
