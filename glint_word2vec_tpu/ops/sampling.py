"""On-device negative sampling from the unigram^0.75 noise distribution.

Reference: the Glint servers draw ``n`` negatives per (center, context) pair
from a shared quantized unigram table, seeded by the client so all servers
draw identically (``matrix.dotprod(..., seed)``, mllib:420-421; SURVEY.md
§2.2). Here the draw happens *inside* the jit-compiled train step from a
replicated alias table (see corpus/alias.py): O(1) per draw, exact
distribution, reproducible from the step's PRNG key — the same contract
(seed -> identical negatives everywhere) without a server round-trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_negatives(
    key: jax.Array,
    prob: jax.Array,  # (V,) float32 alias acceptance probabilities
    alias: jax.Array,  # (V,) int32 alias targets
    shape: tuple,
) -> jax.Array:
    """Draw ``shape`` samples from the alias table: int32 indices in [0, V)."""
    k_key, u_key = jax.random.split(key)
    vocab = prob.shape[0]
    k = jax.random.randint(k_key, shape, 0, vocab, dtype=jnp.int32)
    u = jax.random.uniform(u_key, shape, dtype=jnp.float32)
    return jnp.where(u < prob[k], k, alias[k])


def sample_negatives_per_row(
    key: jax.Array,
    prob: jax.Array,  # (V,) float32 alias acceptance probabilities
    alias: jax.Array,  # (V,) int32 alias targets
    rows: jax.Array,  # (B,) int32 GLOBAL batch-row indices
    shape_per_row: tuple,
) -> jax.Array:
    """Per-batch-row negative draws keyed by global row index.

    Returns ``(B, *shape_per_row)`` int32 samples where row ``i``'s draws
    depend only on ``(key, rows[i])``. This is the sharded-sampling form of
    the reference's seed contract (servers all draw identically from the
    broadcast seed, mllib:420-421): a data rank holding global rows
    [r0, r0+Bl) reproduces exactly the draws a single-rank run makes for
    those rows, while doing only O(local rows) sampling work — no rank ever
    draws the global batch (round-3 directive: no ``B_global`` in the
    sampled shape).
    """
    # Domain-separate before the per-row fold: user step keys are often
    # low-entropy (PRNGKey(step)), and one threefry round over both a small
    # key and a small row id can yield streams unlucky enough to matter in
    # tiny-vocab training; the constant mix adds a full extra round.
    base = jax.random.fold_in(key, 0x6E656773)  # "negs"
    keys = jax.vmap(lambda r: jax.random.fold_in(base, r))(rows)
    return jax.vmap(
        lambda k: sample_negatives(k, prob, alias, shape_per_row)
    )(keys)
