"""On-device negative sampling from the unigram^0.75 noise distribution.

Reference: the Glint servers draw ``n`` negatives per (center, context) pair
from a shared quantized unigram table, seeded by the client so all servers
draw identically (``matrix.dotprod(..., seed)``, mllib:420-421; SURVEY.md
§2.2). Here the draw happens *inside* the jit-compiled train step from a
replicated alias table (see corpus/alias.py): O(1) per draw, exact
distribution, reproducible from the step's PRNG key — the same contract
(seed -> identical negatives everywhere) without a server round-trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_negatives(
    key: jax.Array,
    prob: jax.Array,  # (V,) float32 alias acceptance probabilities
    alias: jax.Array,  # (V,) int32 alias targets
    shape: tuple,
) -> jax.Array:
    """Draw ``shape`` samples from the alias table: int32 indices in [0, V)."""
    k_key, u_key = jax.random.split(key)
    vocab = prob.shape[0]
    k = jax.random.randint(k_key, shape, 0, vocab, dtype=jnp.int32)
    u = jax.random.uniform(u_key, shape, dtype=jnp.float32)
    return jnp.where(u < prob[k], k, alias[k])
