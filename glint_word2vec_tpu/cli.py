"""Command-line interface: train / query / inspect without writing code.

The reference's operational entry points are spark-submit invocations — the
trainer app and the standalone ``glint.Main`` PS-cluster launcher
(README.md:45-57, build.sbt:42-59, SURVEY.md §3.5). On TPU there is no
separate server process to launch (the "cluster" is the device mesh inside
this process), so the CLI collapses to:

  python -m glint_word2vec_tpu.cli train   --corpus c.txt --output m/ [...]
  python -m glint_word2vec_tpu.cli fit-stream --corpus - --publish-dir p/ [...]
  python -m glint_word2vec_tpu.cli synonyms --model m/ --word w [-n 10]
  python -m glint_word2vec_tpu.cli analogy  --model m/ --positive a b --negative c
  python -m glint_word2vec_tpu.cli transform --model m/ --sentence "w1 w2 w3"
  python -m glint_word2vec_tpu.cli info     --model m/
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from glint_word2vec_tpu.obs.canary import TrainingDiverged


def _add_train(sub):
    p = sub.add_parser("train", help="train a model from a text corpus")
    p.add_argument("--corpus", required=True, help="text file, one sentence per line")
    p.add_argument("--output", required=True, help="model output directory")
    p.add_argument("--lowercase", action="store_true")
    p.add_argument("--vector-size", type=int, default=100)
    p.add_argument("--window", type=int, default=5)
    p.add_argument("--step-size", type=float, default=0.01875)
    p.add_argument("--batch-size", type=int, default=1024)
    p.add_argument("--negatives", type=int, default=5)
    p.add_argument("--subsample-ratio", type=float, default=0.0)
    p.add_argument("--min-count", type=int, default=5)
    p.add_argument("--iterations", type=int, default=1)
    p.add_argument("--max-sentence-length", type=int, default=1000)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--num-partitions", type=int, default=1,
                   help="data-parallel mesh axis (reference numPartitions)")
    p.add_argument("--num-shards", type=int, default=1,
                   help="model-parallel mesh axis (reference numParameterServers)")
    p.add_argument("--dtype", choices=["float32", "bfloat16"], default="float32")
    p.add_argument("--compute-dtype", choices=["float32", "bfloat16"],
                   default=None,
                   help="MXU operand dtype for the step's dense "
                        "contractions (f32 accumulation either way)")
    p.add_argument("--layout", choices=["rows", "dims"], default="rows",
                   help="model-axis table partitioning: vocab rows or "
                        "embedding dims (CIKM column sharding)")
    p.add_argument("--steps-per-call", type=int, default=16,
                   help="minibatches per device dispatch (on-device scan)")
    p.add_argument("--shared-negatives", type=int, default=0,
                   help="shared noise-pool size per step "
                        "(0 = per-pair reference semantics)")
    p.add_argument("--packing", choices=["dense", "grid"], default="dense",
                   help="device-corpus dispatch shape: dense pair "
                        "packing (default — valid pairs compacted into "
                        "dense pair batches on device) or the legacy "
                        "grid window batches (~43%% live lanes at "
                        "window 5)")
    p.add_argument("--exchange", choices=["none", "sparse", "dense"],
                   default="none",
                   help="cross-replica reconciliation for multi-process "
                        "runs (ISSUE 15): sparse ships only touched-row "
                        "(ids, deltas) between data-parallel replicas "
                        "after every dispatch group; dense ships full "
                        "table deltas (parity baseline); none keeps the "
                        "SPMD global-mesh path")
    p.add_argument("--exchange-capacity", type=int, default=0,
                   help="fixed touched-row buffer capacity per exchange "
                        "sync (0 = auto-sized from the dispatch-group "
                        "pair budget, then adapted down from observed "
                        "telemetry; nonzero pins it)")
    p.add_argument("--exchange-wire", choices=["fp32", "bf16", "int8"],
                   default="fp32",
                   help="sparse exchange payload encoding (ISSUE 16): "
                        "fp32 exact, bf16 half-width, or int8 with "
                        "per-row maxabs scales and error-feedback "
                        "residual carry (unbiased update stream); "
                        "dense/spill/flush rounds always ship fp32")
    p.add_argument("--exchange-every", type=int, default=1,
                   help="coalesce this many dispatch groups into one "
                        "exchange round (hot rows repeatedly touched "
                        "in the window cost one wire row); 1 = sync "
                        "every group")
    p.add_argument("--exchange-topology", choices=["flat", "twolevel"],
                   default="flat",
                   help="exchange sync topology: flat allgather, or "
                        "twolevel — exact intra-node hop + leaders-only "
                        "quantized inter-node hop (GLINT_RANKS_PER_NODE "
                        "sets the node size)")
    p.add_argument("--exchange-shard",
                   choices=["roundrobin", "locality"],
                   default="roundrobin",
                   help="replica corpus sharding: roundrobin interleave "
                        "or locality (sentences clustered by rarest "
                        "token to concentrate per-rank touched rows)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable epoch-granular checkpoint/resume")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="epochs between checkpoints (default 1). Saves "
                        "are asynchronous by default — the fit thread "
                        "blocks only for the device->host snapshot "
                        "copy; write + commit run on a background "
                        "thread (GLINT_SYNC_CKPT=1 forces blocking "
                        "saves)")
    p.add_argument("--metrics-out", default=None,
                   help="write training metrics JSON here (atomic write)")
    obs = p.add_argument_group(
        "observability",
        "run-wide observability: live heartbeat, span event log, "
        "divergence canary (all opt-in, zero overhead when off)",
    )
    obs.add_argument("--status-port", type=int, default=None,
                     help="serve a live training heartbeat on this port: "
                          "GET /healthz and /metrics (JSON by default; "
                          "?format=prometheus for scrape-ready text). "
                          "0 binds an ephemeral port")
    obs.add_argument("--status-host", default="127.0.0.1",
                     help="heartbeat bind address (default 127.0.0.1)")
    obs.add_argument("--status-file", default=None,
                     help="atomically mirror the status snapshot JSON to "
                          "this path (for multihost workers that can't "
                          "bind ports)")
    obs.add_argument("--event-log", default=None,
                     help="JSONL span/event log of the fit's phases "
                          "(subsample-compact, host batching, device "
                          "dispatch, checkpoints) plus engine events "
                          "(table mutations, query-shape compiles)")
    obs.add_argument("--event-capacity", type=int, default=65536,
                     help="in-memory event ring bound; overflow is "
                          "counted, never unbounded (default 65536)")
    obs.add_argument("--steptime-out", default=None,
                     help="write the per-run step-time attribution "
                          "ledger (STEPTIME.json) here at fit end: "
                          "fit-thread wall seconds by phase (dispatch, "
                          "readback_harvest, producer_wait, compact, "
                          "checkpoint, other) + per-phase span-duration "
                          "quantiles")
    obs.add_argument("--chrome-trace", default=None,
                     help="write the event log as chrome://tracing / "
                          "Perfetto JSON at run end (merge with device "
                          "xplane tables via scripts/trace_summarize.py "
                          "--host-spans)")
    obs.add_argument("--canary", choices=["off", "warn", "abort"],
                     default="off",
                     help="divergence canary over a rolling loss window: "
                          "'warn' logs and records an event; 'abort' "
                          "writes a final ckpt-diverged snapshot + "
                          "flushes the event log, then fails the run")
    obs.add_argument("--canary-window", type=int, default=64,
                     help="rolling loss window size (default 64)")
    obs.add_argument("--canary-factor", type=float, default=10.0,
                     help="trip when loss exceeds factor x the window "
                          "median (default 10.0); NaN/Inf always trips")
    obs.add_argument("--canary-check-every", type=int, default=32,
                     help="steps between canary loss syncs; each check "
                          "blocks the async dispatch pipeline for one "
                          "device sync (default 32)")
    dist_g = p.add_argument_group(
        "distributed",
        "multi-process bring-up (the supervise subcommand fills these "
        "in per worker; set them by hand for custom launchers)",
    )
    dist_g.add_argument("--coordinator", default=None,
                        help="host:port of the jax.distributed "
                             "coordinator (process 0 binds it)")
    dist_g.add_argument("--num-processes", type=int, default=None,
                        help="total worker processes in the gang")
    dist_g.add_argument("--process-id", type=int, default=None,
                        help="this worker's rank in [0, num-processes)")
    p.add_argument("--fasttext", action="store_true",
                   help="train the subword (fastText-style) family")
    p.add_argument("--min-n", type=int, default=3,
                   help="min char-ngram length (fastText family)")
    p.add_argument("--max-n", type=int, default=6,
                   help="max char-ngram length (fastText family)")
    p.add_argument("--bucket", type=int, default=2_000_000,
                   help="subword hash-bucket rows (fastText family)")
    p.add_argument("--max-subwords", type=int, default=32,
                   help="max subword rows per word (fastText family)")


def _add_fit_stream(sub):
    p = sub.add_parser(
        "fit-stream",
        help="incremental (ISGNS) training on an unbounded sentence "
             "stream: online vocab growth, adaptive distributions, and "
             "committed generation publishing a `serve "
             "--watch-checkpoint` fleet hot-swaps under load",
    )
    p.add_argument("--corpus", default="-",
                   help="sentence source, one per line: a file path, or "
                        "'-' (default) for stdin — pipe a live feed in")
    p.add_argument("--follow", action="store_true",
                   help="tail the --corpus file forever (tail -f "
                        "semantics): keep polling for appended lines "
                        "instead of stopping at EOF")
    p.add_argument("--lowercase", action="store_true")
    p.add_argument("--publish-dir", default=None,
                   help="publish committed model generations here "
                        "(gen-NNNNNN dirs + LATEST.json pointer) for "
                        "serving replicas to hot-swap")
    p.add_argument("--publish-every", type=float, default=30.0,
                   help="seconds between generation publishes "
                        "(default 30; whichever of the time/word "
                        "cadences fires first publishes)")
    p.add_argument("--publish-words", type=int, default=None,
                   help="also publish every N trained words")
    p.add_argument("--output", default=None,
                   help="save the final model here when the stream ends")
    p.add_argument("--bootstrap-words", type=int, default=10000,
                   help="stream prefix scanned batch-style to seed the "
                        "base vocabulary (default 10000 words)")
    p.add_argument("--buffer-words", type=int, default=65536,
                   help="mini-epoch buffer capacity in words (fixed "
                        "shape: every round reuses the same compiled "
                        "programs; default 65536)")
    p.add_argument("--extra-rows", type=int, default=1024,
                   help="spare table rows reserved for online vocab "
                        "growth — the promotion budget (default 1024)")
    p.add_argument("--refresh-words", type=int, default=None,
                   help="kept-word cadence for recomputing the adaptive "
                        "noise/subsample distributions (default: one "
                        "buffer)")
    p.add_argument("--max-words", type=int, default=None,
                   help="stop after training this many words (bounded "
                        "runs/smokes; default: run until the stream ends)")
    p.add_argument("--max-seconds", type=float, default=None,
                   help="stop after this much wall time")
    p.add_argument("--vector-size", type=int, default=100)
    p.add_argument("--window", type=int, default=5)
    p.add_argument("--step-size", type=float, default=0.01875)
    p.add_argument("--batch-size", type=int, default=1024)
    p.add_argument("--negatives", type=int, default=5)
    p.add_argument("--subsample-ratio", type=float, default=0.0)
    p.add_argument("--min-count", type=int, default=5,
                   help="bootstrap admission AND promotion threshold "
                        "(a candidate's guaranteed sketch count must "
                        "clear it)")
    p.add_argument("--max-sentence-length", type=int, default=1000)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--num-partitions", type=int, default=1)
    p.add_argument("--num-shards", type=int, default=1)
    p.add_argument("--steps-per-call", type=int, default=16)
    p.add_argument("--metrics-out", default=None,
                   help="write final training metrics JSON here "
                        "(atomic write)")
    obs = p.add_argument_group(
        "observability",
        "live heartbeat with the streaming gauge set: stream lag, "
        "vocab growth, distribution drift, publish cadence "
        "(glint_stream_* in the Prometheus exposition)",
    )
    obs.add_argument("--status-port", type=int, default=None,
                     help="serve /healthz + /metrics for the stream "
                          "trainer (0 binds an ephemeral port)")
    obs.add_argument("--status-host", default="127.0.0.1")
    obs.add_argument("--status-file", default=None,
                     help="atomically mirror the status snapshot JSON "
                          "to this path")
    obs.add_argument("--event-log", default=None,
                     help="JSONL span/event log (stream_fill, "
                          "device_steps, publish, table mutations)")


def _add_ann_flags(p):
    ann = p.add_argument_group(
        "approximate serving (ANN index)",
        "two-stage device top-k: k-means centroids trained on-device "
        "from the live table, coarse scores pick nprobe clusters, "
        "exact rerank inside them — O(sqrt(V)*d)-ish per query with a "
        "measured recall@10 gate against the exact path (per-request "
        '{"exact": true} always escapes to the exact masked GEMM)',
    )
    ann.add_argument("--ann", action="store_true",
                     help="enable the approximate /synonyms path "
                          "(built + recall-gated before the port "
                          "binds; refreshed on every hot-swap)")
    ann.add_argument("--ann-clusters", type=int, default=-1,
                     help="coarse cluster count (-1 auto: "
                          "next_pow2(sqrt(rows)))")
    ann.add_argument("--ann-nprobe", type=int, default=8,
                     help="clusters probed per query (default 8)")
    ann.add_argument("--ann-iters", type=int, default=6,
                     help="k-means sweeps per index build (default 6)")
    ann.add_argument("--ann-sample", type=int, default=65536,
                     help="rows sampled for centroid training "
                          "(default 65536; the full table is always "
                          "assigned)")
    ann.add_argument("--ann-recall-gate", type=float, default=0.95,
                     help="minimum measured recall@10 vs the exact "
                          "path; below it the exact path keeps "
                          "serving (default 0.95)")
    ann.add_argument("--ann-recall-sample", type=int, default=64,
                     help="query rows sampled per recall measurement "
                          "(default 64)")


def _ann_kwargs(args) -> dict:
    return dict(
        ann=args.ann,
        ann_clusters=args.ann_clusters,
        ann_nprobe=args.ann_nprobe,
        ann_iters=args.ann_iters,
        ann_sample=args.ann_sample,
        ann_recall_gate=args.ann_recall_gate,
        ann_recall_sample=args.ann_recall_sample,
    )


def _add_query(sub):
    p = sub.add_parser("synonyms", help="nearest neighbors of a word")
    p.add_argument("--model", required=True)
    p.add_argument("--word", required=True)
    p.add_argument("-n", "--num", type=int, default=10)

    p = sub.add_parser("analogy", help="a is to b as c is to ?")
    p.add_argument("--model", required=True)
    p.add_argument("--positive", nargs="+", required=True)
    p.add_argument("--negative", nargs="+", default=[])
    p.add_argument("-n", "--num", type=int, default=10)

    p = sub.add_parser("transform", help="embed a sentence (mean of word vectors)")
    p.add_argument("--model", required=True)
    p.add_argument("--sentence", required=True, help="whitespace-tokenized")

    p = sub.add_parser("info", help="model metadata")
    p.add_argument("--model", required=True)

    p = sub.add_parser(
        "serve",
        help="serve a saved model over HTTP (the separate-PS-cluster "
             "deployment analogue: trainers/clients come and go, the "
             "model stays resident)",
    )
    p.add_argument("--model", default=None,
                   help="saved model directory (optional when "
                        "--watch-checkpoint names a publish dir: the "
                        "newest committed generation boots the server)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8801)
    p.add_argument("--watch-checkpoint", default=None, metavar="DIR",
                   help="follow a fit-stream publish directory: each "
                        "new committed generation (LATEST.json) is "
                        "staged off the request path and hot-swapped "
                        "into the live engine — no dropped requests, "
                        "no post-warmup compiles (POST /reload forces "
                        "an immediate poll)")
    p.add_argument("--watch-poll", type=float, default=1.0,
                   help="seconds between LATEST.json polls (default 1)")
    p.add_argument("--max-batch", type=int, default=64,
                   help="coalesced /synonyms dispatch cap (rounded up to "
                        "a power of two; Q shape buckets warm up to it)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip pre-binding compilation of the serving "
                        "shape family (first requests then pay jit "
                        "compiles)")
    p.add_argument("--cache-size", type=int, default=65536,
                   help="synonym result-cache entries (0 disables); "
                        "invalidated wholesale on any table mutation")
    p.add_argument("--port-file", default=None, metavar="FILE",
                   help="write the bound {host, port} JSON here once "
                        "the server is warmed and listening (the "
                        "fleet launcher's readiness barrier for "
                        "--port 0)")
    p.add_argument("--trace-log", default=None, metavar="FILE",
                   help="record request-path phase spans (tail-sampled "
                        "distributed tracing) to this JSONL sink; "
                        "stitch per-process sinks with `cli "
                        "trace-merge` and open the result in Perfetto")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="arm the anomaly flight recorder: a shed burst "
                        "or an SLO fast-burn writes a postmortem "
                        "bundle (recent spans + full metrics) here")
    _add_ann_flags(p)
    over = p.add_argument_group(
        "overload protection",
        "bounded admission + per-request deadlines + degraded "
        "cache-only mode, so a traffic spike sheds load instead of "
        "queueing without bound (counters on /metrics)",
    )
    over.add_argument("--max-inflight", type=int, default=256,
                      help="admission high-water mark: device-touching "
                           "requests beyond this many in flight are "
                           "shed with 429 + Retry-After (0 disables; "
                           "default 256)")
    over.add_argument("--request-deadline", type=float, default=30.0,
                      help="per-request deadline seconds: a request "
                           "that cannot reach the device in time is "
                           "answered 504 instead of occupying a "
                           "dispatch slot (0 disables; default 30)")
    over.add_argument("--degraded-after", type=float, default=5.0,
                      help="device-lock hold seconds after which the "
                           "server enters degraded cache-only mode "
                           "(serve cache hits, shed misses with 429) "
                           "until the lock frees (0 disables; "
                           "default 5)")
    mm = p.add_argument_group(
        "multi-model serving (ISSUE 20)",
        "one server hosting N models behind one port: route with the "
        "/m/<id>/ path prefix or the X-Glint-Model header (no id = the "
        "default model, full back-compat); same-shape models share "
        "every compiled program, so model #2..N loads with zero "
        "compiles",
    )
    mm.add_argument("--add-model", action="append", default=[],
                    metavar="ID=DIR",
                    help="load an extra named model into the catalog "
                         "(repeatable); DIR is a saved model dir or a "
                         "committed publish generation")
    mm.add_argument("--model-memory-budget", default=None,
                    metavar="BYTES",
                    help="device-memory budget for resident tables "
                         "(suffixes kb/mb/gb); over budget, the "
                         "least-recently-used unpinned model is staged "
                         "out to its committed snapshot and staged "
                         "back in off the request path on first miss")
    mm.add_argument("--watch-models", default=None, metavar="DIR",
                    help="catalog root: each subdirectory with a "
                         "LATEST.json is one model's publish dir "
                         "(subdir name = model id), followed with its "
                         "own hot-swap watcher")

    p = sub.add_parser(
        "serve-fleet",
        help="launch N serving replicas following one model (or one "
             "publish dir) behind a front load balancer: round-robin "
             "spread, overload-aware retry on the replicas' 429/503 "
             "backpressure, one merged fleet /metrics exposition",
    )
    p.add_argument("--model", default=None,
                   help="saved model directory every replica loads")
    p.add_argument("--watch-checkpoint", default=None, metavar="DIR",
                   help="publish dir every replica follows (each "
                        "committed generation hot-swaps the WHOLE "
                        "fleet, index refresh included)")
    p.add_argument("--watch-poll", type=float, default=1.0)
    p.add_argument("--replicas", type=int, default=2,
                   help="serving process count (default 2)")
    p.add_argument("--host", default="127.0.0.1",
                   help="balancer bind address")
    p.add_argument("--port", type=int, default=8800,
                   help="balancer port (0 = ephemeral; replicas "
                        "always bind ephemeral ports)")
    p.add_argument("--port-file", default=None, metavar="FILE",
                   help="write the balancer's bound {host, port} here "
                        "once the fleet is up")
    p.add_argument("--replica-log-dir", default=None, metavar="DIR",
                   help="capture one replica-N.log per process "
                        "(default: replicas inherit stderr)")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="end-to-end distributed tracing root: the "
                        "balancer records to balancer.jsonl, every "
                        "replica to replica-N.jsonl, anomaly bundles "
                        "land under flight/ — stitch with `cli "
                        "trace-merge <DIR> --out trace.json`")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--cache-size", type=int, default=65536)
    p.add_argument("--max-inflight", type=int, default=256)
    p.add_argument("--request-deadline", type=float, default=30.0)
    p.add_argument("--degraded-after", type=float, default=5.0)
    _add_ann_flags(p)
    heal = p.add_argument_group(
        "self-healing (ISSUE 14)",
        "replica supervision (waitpid + /healthz probes with a launch-"
        "generation handshake), probe-driven circuit breaking, and — "
        "with --watch-checkpoint — rolling generation rollout behind a "
        "shadow-canary promotion gate",
    )
    heal.add_argument("--max-restarts", type=int, default=3,
                      help="per-replica relaunch budget before the "
                           "replica is left down (fleet serves from "
                           "the survivors; default 3)")
    heal.add_argument("--backoff-base", type=float, default=1.0,
                      help="first relaunch delay seconds (doubles per "
                           "restart, capped at --backoff-cap)")
    heal.add_argument("--backoff-cap", type=float, default=30.0)
    heal.add_argument("--hang-kill-after", type=float, default=10.0,
                      help="continuous probe-failure seconds after "
                           "which a live replica process is killed "
                           "and relaunched (hung-replica detection)")
    heal.add_argument("--probe-interval", type=float, default=0.5,
                      help="seconds between active /healthz probes "
                           "per replica")
    heal.add_argument("--probe-timeout", type=float, default=2.0)
    heal.add_argument("--breaker-failures", type=int, default=3,
                      help="consecutive probe/connect failures that "
                           "eject a replica from rotation (breaker "
                           "opens)")
    heal.add_argument("--breaker-successes", type=int, default=2,
                      help="consecutive half-open trial successes "
                           "that readmit it")
    heal.add_argument("--breaker-open-seconds", type=float, default=2.0,
                      help="open-breaker cooldown before half-open "
                           "trials begin")
    heal.add_argument("--replica0-env", action="append", default=[],
                      metavar="KEY=VAL",
                      help="env var applied to replica 0's FIRST "
                           "launch only (repeatable) — the chaos-drill "
                           "seam for arming a GLINT_FAULTS schedule on "
                           "one replica without re-killing every "
                           "relaunch")
    heal.add_argument("--uncoordinated-watch", action="store_true",
                      help="legacy behavior: every replica follows "
                           "--watch-checkpoint itself (simultaneous "
                           "fleet-wide swaps, no rolling rollout, no "
                           "canary gate)")
    can = p.add_argument_group(
        "shadow-canary promotion gate",
        "before a rolling rollout proceeds, the candidate generation "
        "serves MIRRORED traffic on one ejected replica and must "
        "agree with the live fleet (top-k overlap) — regression means "
        "automatic hold-back, counted on /metrics, with the candidate "
        "left on disk",
    )
    can.add_argument("--no-canary", action="store_true",
                     help="skip the canary gate (rolling rollout "
                          "proceeds directly)")
    can.add_argument("--canary-mirror-every", type=int, default=4,
                     help="mirror every Nth live /synonyms|/analogy "
                          "request to the canary (default 4)")
    can.add_argument("--canary-min-scores", type=int, default=8,
                     help="responses to score before deciding "
                          "(default 8)")
    can.add_argument("--canary-mirror-seconds", type=float, default=10.0,
                     help="max seconds to collect mirrored responses "
                          "(default 10)")
    can.add_argument("--canary-agreement", type=float, default=0.6,
                     help="mean top-k agreement the candidate must "
                          "clear to promote (default 0.6)")
    can.add_argument("--canary-top-k", type=int, default=10)
    can.add_argument("--canary-probes", default=None, metavar="FILE",
                     help="JSON list of deterministic probe requests "
                          '([{"path": "/synonyms", "body": {...}}, '
                          "...]) posted to both the live fleet and "
                          "the canary and scored for agreement — the "
                          "vienna/berlin + capital-of analogy gates, "
                          "restated as live-vs-candidate checks")
    dp = p.add_argument_group(
        "demand-driven fleet (ISSUE 19)",
        "multi-process balancer data plane (N processes sharing one "
        "listen port), warm-spare autoscaling driven by shed-rate/p95/"
        "burn signals, and per-tenant QoS admission at the front door",
    )
    dp.add_argument("--balancer-procs", type=int, default=1,
                    help="balancer process count sharing the listen "
                         "port (SO_REUSEPORT, falling back to an "
                         "inherited listener fd); 1 = the classic "
                         "single in-process balancer (default)")
    dp.add_argument("--warm-spares", type=int, default=0,
                    help="extra replicas launched and fully warmed at "
                         "boot but HELD out of rotation as spares; "
                         "the autoscaler readmits them under load "
                         "(scale-up is never a cold boot)")
    dp.add_argument("--autoscale-interval", type=float, default=0.5,
                    help="autoscaler policy-evaluation period seconds "
                         "(default 0.5)")
    dp.add_argument("--autoscale-up-shed-rate", type=float, default=1.0,
                    help="fleet shed rate (sheds/sec, QoS sheds "
                         "included) that counts as scale-up pressure "
                         "(default 1.0)")
    dp.add_argument("--autoscale-up-p95-ms", type=float, default=None,
                    help="forward-path p95 ms that counts as scale-up "
                         "pressure (default: the SLO latency target, "
                         "GLINT_SLO_LATENCY_MS or 250)")
    dp.add_argument("--autoscale-up-window", type=float, default=1.0,
                    help="seconds pressure must be sustained before a "
                         "readmit (default 1)")
    dp.add_argument("--autoscale-down-window", type=float, default=10.0,
                    help="seconds of idle before a live replica is "
                         "parked back to spare (default 10)")
    dp.add_argument("--autoscale-cooldown", type=float, default=5.0,
                    help="minimum seconds between any two autoscale "
                         "transitions (default 5)")
    dp.add_argument("--qos-tenant-rate", type=float, default=None,
                    help="per-tenant token-bucket refill rate "
                         "(requests/sec, tenant from X-Glint-Tenant, "
                         "'default' bucket otherwise); unset = no "
                         "tenant quotas")
    dp.add_argument("--qos-tenant-burst", type=float, default=None,
                    help="per-tenant bucket depth (default 2x rate)")
    dp.add_argument("--qos-bulk-max-inflight", type=int, default=None,
                    help="concurrent in-flight cap for the bulk "
                         "priority class (X-Glint-Priority: bulk); "
                         "unset = no class cap")
    fmm = p.add_argument_group(
        "multi-model fleet (ISSUE 20)",
        "every replica hosts the same model catalog behind the one "
        "balancer port; each watched model gets its OWN rolling "
        "rollout + canary gate, so one model's LATEST.json move never "
        "touches another model's replica state",
    )
    fmm.add_argument("--add-model", action="append", default=[],
                     metavar="ID=DIR",
                     help="extra named model every replica loads "
                          "(repeatable)")
    fmm.add_argument("--watch-model", action="append", default=[],
                     metavar="ID=DIR",
                     help="publish dir followed for model ID with a "
                          "per-model rolling rollout (repeatable; "
                          "also loads the model at its newest "
                          "committed generation when no --add-model "
                          "pins a boot point)")
    fmm.add_argument("--model-memory-budget", default=None,
                     metavar="BYTES",
                     help="per-replica resident-table budget "
                          "(suffixes kb/mb/gb); LRU stage-out beyond "
                          "it")

    p = sub.add_parser(
        "fleet-shard",
        help="INTERNAL: one balancer data-plane shard of `serve-fleet "
             "--balancer-procs N` — accepts from the shared fleet "
             "port, driven by the supervisor over a private control "
             "channel; never invoke by hand",
    )
    p.add_argument("--config", required=True, metavar="FILE",
                   help="shard config JSON written by the supervisor")

    p = sub.add_parser(
        "supervise",
        help="run a train command under the elastic supervisor: gang "
             "launch, crash/hang detection, teardown, resume from the "
             "last committed checkpoint with capped backoff",
    )
    p.add_argument("--workers", type=int, default=1,
                   help="worker process count (1 = supervised "
                        "single-process fit; >1 launches a "
                        "jax.distributed gang on a local coordinator)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="gang restart budget before giving up")
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="first restart delay seconds (doubles per "
                        "restart, capped at --backoff-cap)")
    p.add_argument("--backoff-cap", type=float, default=30.0)
    p.add_argument("--heartbeat-stale", type=float, default=120.0,
                   help="status-file heartbeat age that counts as a "
                        "hang (0 disables hang detection)")
    p.add_argument("--startup-grace", type=float, default=600.0,
                   help="seconds a fresh worker may run without a "
                        "first heartbeat (cold jax compiles are slow)")
    p.add_argument("--supervise-dir", default=None,
                   help="status files + worker logs directory "
                        "(default: <checkpoint-dir>/supervisor)")
    p.add_argument("--report-out", default=None,
                   help="write the supervisor report JSON here too "
                        "(it always prints to stdout): restarts, "
                        "per-restart detect->relaunch/heartbeat "
                        "latency, and the postmortem bundle paths the "
                        "flight recorder collected")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve the MERGED gang observability endpoint "
                        "on this port (0 = ephemeral): one /metrics "
                        "(JSON; ?format=prometheus for scrape-ready "
                        "text) + /healthz for the whole gang — summed "
                        "counters, per-rank words/sec, the rank_skew "
                        "straggler gauge, merged step-time ledger — "
                        "generation-stamped so pre-restart scrapes are "
                        "never mixed in")
    p.add_argument("--metrics-host", default="127.0.0.1",
                   help="merged-endpoint bind address")
    p.add_argument("--join-serving", action="append", default=[],
                   metavar="URL",
                   help="serving-replica JSON /metrics URL to join "
                        "into the merged exposition (repeatable; "
                        "scraped per request, replica failures "
                        "reported, never fatal)")
    p.add_argument("--rank0-env", action="append", default=[],
                   metavar="KEY=VAL",
                   help="env var applied to rank 0's FIRST launch only "
                        "(generation 0, repeatable) — the chaos-drill "
                        "seam for arming a GLINT_FAULTS schedule "
                        "without re-killing every relaunch")
    p.add_argument(
        "train_args", nargs=argparse.REMAINDER,
        help="the train command to supervise: everything after the "
             "supervise flags, e.g. `supervise --workers 2 train "
             "--corpus c.txt --output m/ --checkpoint-dir ck/`. "
             "--checkpoint-dir is REQUIRED (recovery resumes from it); "
             "the supervisor appends per-worker --status-file and "
             "distributed flags itself",
    )

    p = sub.add_parser(
        "transform-file",
        help="bulk-embed a sentence file into resumable .npy vector "
             "shards (the offline DataFrame-transform analogue: "
             "compile-once packed pull-average batches at full device "
             "utilization)",
    )
    p.add_argument("--model", required=True)
    p.add_argument("--input", required=True,
                   help="one whitespace-tokenized sentence per line; "
                        "blank/all-OOV lines become zero vectors — "
                        "output row i always aligns with input line i")
    p.add_argument("--out", required=True,
                   help="shard directory (per-rank rank-NNNN/ subdirs "
                        "when --workers > 1)")
    p.add_argument("--rows", type=int, default=1024,
                   help="sentences per packed device batch — the fixed "
                        "row bucket of the compiled family "
                        "(default 1024)")
    p.add_argument("--max-len", type=int, default=256,
                   help="token cap per sentence (longer tails are "
                        "truncated; bounds the warmed pow2 length "
                        "family, default 256)")
    p.add_argument("--shard-size", type=int, default=8192,
                   help="sentences per output shard, rounded up to a "
                        "--rows multiple (default 8192)")
    p.add_argument("--lowercase", action="store_true")
    p.add_argument("--prefetch", type=int, default=2,
                   help="producer batches buffered ahead of the device "
                        "(default 2: double-buffered)")
    p.add_argument("--no-deep-verify", action="store_true",
                   help="resume scan checks shard sizes only instead "
                        "of re-hashing committed payloads")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip the pre-stream compile warmup (steady "
                        "state then pays the jit compiles)")
    p.add_argument("--metrics-out", default=None,
                   help="write the run stats JSON here too (always "
                        "printed to stdout)")
    p.add_argument("--status-file", default=None,
                   help="atomically mirror the transform heartbeat "
                        "snapshot JSON to this path")
    p.add_argument("--status-port", type=int, default=None,
                   help="serve /healthz + /metrics for this transform "
                        "run (0 binds an ephemeral port)")
    p.add_argument("--event-log", default=None)
    p.add_argument("--rank", type=int, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--world", type=int, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--workers", type=int, default=1,
                   help="rank-parallel worker count under the elastic "
                        "supervisor: each rank owns a contiguous input "
                        "span and a private shard directory, crashed "
                        "ranks relaunch and resume from their own "
                        "committed shards")
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--heartbeat-stale", type=float, default=120.0,
                   help="status-file heartbeat age that counts as a "
                        "hang (0 disables hang detection)")
    p.add_argument("--startup-grace", type=float, default=600.0)
    p.add_argument("--supervise-dir", default=None,
                   help="supervisor status/log directory (default "
                        "<out>/supervisor)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="merged gang /metrics + /healthz endpoint "
                        "(supervisor mode; 0 = ephemeral)")
    p.add_argument("--report-out", default=None,
                   help="write the supervisor report JSON here too")

    p = sub.add_parser(
        "synonyms-dump",
        help="all-vocab top-k neighbor dump (JSONL) and/or k-NN graph "
             "arrays — whole-table batch top-k, the ANN index's best "
             "amortization regime",
    )
    p.add_argument("--model", required=True)
    p.add_argument("--out", default=None,
                   help='JSONL output path (one {"word", "synonyms"} '
                        "object per vocab word, self-match excluded)")
    p.add_argument("--graph-out", default=None, metavar="PREFIX",
                   help="also write <PREFIX>.ids.npy / <PREFIX>.sims"
                        ".npy / <PREFIX>.json k-NN graph arrays "
                        "(int32 neighbor ids padded with -1)")
    p.add_argument("-n", "--num", type=int, default=10)
    p.add_argument("--block", type=int, default=1024,
                   help="vocab rows pulled + queried per device "
                        "dispatch (default 1024)")
    p.add_argument("--metrics-out", default=None)
    _add_ann_flags(p)

    p = sub.add_parser(
        "trace-merge",
        help="stitch per-process request-trace JSONL sinks (a "
             "serve-fleet --trace-dir, or any set of --trace-log / "
             "--event-log files) into one clock-anchored Chrome-trace "
             "/ Perfetto JSON timeline",
    )
    p.add_argument("inputs", nargs="+",
                   help="trace JSONL files, or directories globbed "
                        "for *.jsonl (+ rotated *.jsonl.1)")
    p.add_argument("--out", required=True,
                   help="merged Chrome-trace JSON output path (open "
                        "in ui.perfetto.dev or chrome://tracing)")

    p = sub.add_parser(
        "eval", help="analogy accuracy on a standard question file"
    )
    p.add_argument("--model", required=True)
    p.add_argument("--questions", required=True,
                   help="': section' headers + 'a b c d' rows")
    p.add_argument("--top-k", type=int, default=1)
    p.add_argument("--no-lowercase", action="store_true")


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
    )
    parser = argparse.ArgumentParser(prog="glint_word2vec_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)
    _add_train(sub)
    _add_fit_stream(sub)
    _add_query(sub)
    args = parser.parse_args(argv)
    try:
        return _run(args)
    except TrainingDiverged as e:
        # The canary already wrote the final checkpoint and flushed the
        # event log; the operator needs the reason, not a stack trace.
        print(f"error: training diverged: {e}", file=sys.stderr)
        return 2
    except (KeyError, ValueError, FileNotFoundError) as e:
        # Expected user errors (OOV word, bad path, bad params): one clean
        # line, no traceback.
        msg = e.args[0] if e.args else str(e)
        print(f"error: {msg}", file=sys.stderr)
        return 1


def _argv_value(argv, flag):
    """Last value of ``--flag v`` / ``--flag=v`` in a raw argv list."""
    val = None
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            val = argv[i + 1]
        elif a.startswith(flag + "="):
            val = a.split("=", 1)[1]
    return val


def _run_supervise(args) -> int:
    """The supervise subcommand: a thin CLI shell over
    ``parallel.supervisor.Supervisor``. Runs in a jax-free process —
    the workers own the devices; the supervisor only watches pids and
    status files."""
    import os

    train_args = list(args.train_args)
    if train_args and train_args[0] == "--":
        train_args = train_args[1:]
    if not train_args or train_args[0] != "train":
        print(
            "error: supervise expects the train command to run, e.g. "
            "`supervise --workers 2 train --corpus c.txt --output m/ "
            "--checkpoint-dir ck/`",
            file=sys.stderr,
        )
        return 1
    rest = train_args[1:]
    checkpoint_dir = _argv_value(rest, "--checkpoint-dir")
    if checkpoint_dir is None:
        print(
            "error: supervise requires --checkpoint-dir in the train "
            "arguments (recovery relaunches resume from it)",
            file=sys.stderr,
        )
        return 1
    sup_dir = args.supervise_dir or os.path.join(
        checkpoint_dir, "supervisor"
    )

    rank0_env = {}
    for kv in args.rank0_env:
        if "=" not in kv:
            print(
                f"error: --rank0-env expects KEY=VAL, got {kv!r}",
                file=sys.stderr,
            )
            return 1
        k, v = kv.split("=", 1)
        rank0_env[k] = v

    from glint_word2vec_tpu.parallel.supervisor import (
        Supervisor,
        cli_train_build_argv,
    )

    report = Supervisor(
        cli_train_build_argv(rest),
        args.workers,
        status_dir=sup_dir,
        checkpoint_dir=checkpoint_dir,
        rank_env_first_launch={0: rank0_env} if rank0_env else None,
        heartbeat_stale_seconds=(
            args.heartbeat_stale if args.heartbeat_stale > 0 else None
        ),
        startup_grace_seconds=args.startup_grace,
        max_restarts=args.max_restarts,
        backoff_base_seconds=args.backoff_base,
        backoff_cap_seconds=args.backoff_cap,
        metrics_port=args.metrics_port,
        metrics_host=args.metrics_host,
        serving_urls=args.join_serving,
    ).run()
    out = report.to_dict()
    print(json.dumps(out))
    if args.report_out:
        from glint_word2vec_tpu.utils import atomic_write_json

        atomic_write_json(args.report_out, out)
    return 0 if report.completed else 3


def _run_transform_fleet(args) -> int:
    """``transform-file --workers N``: a device-free supervisor shell
    (the parent never imports jax — the serve-fleet discipline). Each
    rank re-enters this CLI with ``--rank``/``--world``, derives its
    contiguous input span, and owns a private ``rank-NNNN/`` shard
    directory; a crashed or hung rank relaunches and resumes from its
    own committed shards."""
    import os

    from glint_word2vec_tpu.parallel.supervisor import (
        Supervisor,
        cli_transform_build_argv,
    )

    rest = [
        "--model", args.model, "--input", args.input, "--out", args.out,
        "--rows", str(args.rows), "--max-len", str(args.max_len),
        "--shard-size", str(args.shard_size),
        "--prefetch", str(args.prefetch),
    ]
    if args.lowercase:
        rest.append("--lowercase")
    if args.no_deep_verify:
        rest.append("--no-deep-verify")
    if args.no_warmup:
        rest.append("--no-warmup")
    sup_dir = args.supervise_dir or os.path.join(args.out, "supervisor")
    report = Supervisor(
        cli_transform_build_argv(rest),
        args.workers,
        status_dir=sup_dir,
        heartbeat_stale_seconds=(
            args.heartbeat_stale if args.heartbeat_stale > 0 else None
        ),
        startup_grace_seconds=args.startup_grace,
        max_restarts=args.max_restarts,
        metrics_port=args.metrics_port,
    ).run()
    out = report.to_dict()
    print(json.dumps(out))
    if args.report_out:
        from glint_word2vec_tpu.utils import atomic_write_json

        atomic_write_json(args.report_out, out)
    return 0 if report.completed else 3


def _run_transform_file(args, model) -> int:
    """One transform rank (or the whole single-process run): derive the
    input span, wire the transform heartbeat, stream the file."""
    import os

    from glint_word2vec_tpu.batch.transform import (
        count_lines,
        transform_file,
    )

    rank = args.rank or 0
    world = args.world or 1
    out_dir = args.out
    start, end = 0, None
    if world > 1:
        from glint_word2vec_tpu.parallel.distributed import shard_span

        start, end = shard_span(count_lines(args.input), rank, world)
        out_dir = os.path.join(args.out, f"rank-{rank:04d}")
    run = None
    if (args.status_file or args.status_port is not None
            or args.event_log):
        from glint_word2vec_tpu.obs import ObsConfig, start_run

        run = start_run(
            ObsConfig(
                status_file=args.status_file,
                status_port=args.status_port,
                event_log=args.event_log,
            ),
            pipeline="transform", engine=model.engine,
        )
    failed = True
    try:
        stats = transform_file(
            model, args.input, out_dir,
            rows=args.rows, max_len=args.max_len,
            shard_size=args.shard_size, start=start, end=end,
            lowercase=args.lowercase, prefetch_depth=args.prefetch,
            deep_verify=not args.no_deep_verify,
            warmup=not args.no_warmup, obs_run=run,
        )
        failed = False
    finally:
        if run is not None:
            run.close(failed=failed)
    stats["rank"], stats["world"] = rank, world
    print(json.dumps(stats))
    if args.metrics_out:
        from glint_word2vec_tpu.utils import atomic_write_json

        atomic_write_json(args.metrics_out, stats)
    return 0


def _run_synonyms_dump(args, model) -> int:
    from glint_word2vec_tpu.batch.transform import synonyms_dump

    if args.out is None and args.graph_out is None:
        print(
            "error: synonyms-dump needs --out and/or --graph-out",
            file=sys.stderr,
        )
        return 1
    if args.ann:
        eng = model._query_engine()
        eng.configure_ann(
            clusters=args.ann_clusters, nprobe=args.ann_nprobe,
            iters=args.ann_iters, sample=args.ann_sample,
        )
        if eng.ann_index is None:
            eng.adopt_ann(eng.ann_build())
    stats = synonyms_dump(
        model, args.out, num=args.num, block=args.block,
        approximate=args.ann, graph_prefix=args.graph_out,
    )
    print(json.dumps(stats))
    if args.metrics_out:
        from glint_word2vec_tpu.utils import atomic_write_json

        atomic_write_json(args.metrics_out, stats)
    return 0


def _stream_sentences(path: str, follow: bool, lowercase: bool):
    """Tokenized sentences from a file, stdin (``-``), or a followed
    (tail -f) file. The generator is pull-based: a bounded trainer
    (--max-words/--max-seconds) simply stops pulling. While a followed
    file is idle it yields ``[]`` heartbeats so the trainer can honor
    its stop bounds and publish cadence instead of blocking in the
    fill loop; a half-written trailing line (the producer's write
    landed between flushes) is held until its newline arrives so
    partial tokens never reach the counts or the candidate sketch."""
    import time as _time

    def _toks(line):
        return (line.lower() if lowercase else line).split()

    if path != "-" and not follow:
        # Plain file: the batch paths' line streamer already does
        # exactly this (same tokenization policy, one implementation).
        from glint_word2vec_tpu.corpus.vocab import iter_text_file

        yield from iter_text_file(path, lowercase)
        return
    if path == "-":
        try:
            fd = sys.stdin.fileno()
        except (OSError, ValueError, AttributeError):
            fd = None
        if fd is None:
            # Not a real descriptor (tests substituting StringIO):
            # plain blocking iteration, no idle heartbeats possible.
            for line in sys.stdin:
                toks = _toks(line)
                if toks:
                    yield toks
            return
        # A quiet pipe must not pin the trainer inside its fill loop:
        # select with a timeout and yield [] heartbeats while idle, so
        # --max-seconds and --publish-every stay live, holding any
        # half-written trailing line until its newline arrives.
        import codecs
        import os as _os
        import select as _select

        dec = codecs.getincrementaldecoder("utf-8")("replace")
        pending = ""
        while True:
            ready, _, _ = _select.select([fd], [], [], 0.2)
            if not ready:
                yield []  # idle heartbeat
                continue
            chunk = _os.read(fd, 65536)
            if not chunk:  # EOF
                pending += dec.decode(b"", final=True)
                toks = _toks(pending)  # final newline-less line
                if toks:
                    yield toks
                return
            pending += dec.decode(chunk)
            *lines, pending = pending.split("\n")
            for line in lines:
                toks = _toks(line)
                if toks:
                    yield toks
    # --follow: tail the file forever, holding a half-written trailing
    # line until its newline lands.
    with open(path, encoding="utf-8") as f:
        pending = ""
        while True:
            line = f.readline()
            if not line:
                _time.sleep(0.2)
                yield []  # idle heartbeat
                continue
            line = pending + line
            pending = ""
            if not line.endswith("\n"):
                pending = line
                continue
            toks = _toks(line)
            if toks:
                yield toks


def _run_fit_stream(args) -> int:
    from glint_word2vec_tpu import Word2Vec

    obs = None
    if args.status_port is not None or args.status_file or args.event_log:
        from glint_word2vec_tpu.obs import ObsConfig

        obs = ObsConfig(
            event_log=args.event_log,
            status_port=args.status_port,
            status_host=args.status_host,
            status_file=args.status_file,
        )
    w2v = Word2Vec(
        vector_size=args.vector_size,
        window=args.window,
        step_size=args.step_size,
        batch_size=args.batch_size,
        num_negatives=args.negatives,
        subsample_ratio=args.subsample_ratio,
        min_count=args.min_count,
        max_sentence_length=args.max_sentence_length,
        seed=args.seed,
        num_partitions=args.num_partitions,
        num_shards=args.num_shards,
        steps_per_call=args.steps_per_call,
        obs=obs,
    )
    model = w2v.fit_stream(
        _stream_sentences(args.corpus, args.follow, args.lowercase),
        publish_dir=args.publish_dir,
        bootstrap_words=args.bootstrap_words,
        buffer_words=args.buffer_words,
        extra_rows=args.extra_rows,
        refresh_words=args.refresh_words,
        publish_seconds=args.publish_every,
        publish_words=args.publish_words,
        max_words=args.max_words,
        max_seconds=args.max_seconds,
    )
    if args.output:
        model.save(args.output)
    print(json.dumps({
        **({"saved": args.output} if args.output else {}),
        **(model.training_metrics or {}),
    }))
    if args.metrics_out:
        from glint_word2vec_tpu.utils import atomic_write_json

        atomic_write_json(args.metrics_out, model.training_metrics)
    return 0


def _run_fleet_shard(args) -> int:
    """``fleet-shard``: one subprocess shard of the multi-process
    balancer data plane. Device-free — it proxies bytes."""
    from glint_word2vec_tpu.fleet import run_balancer_shard

    return run_balancer_shard(args.config)


def _parse_model_specs(pairs, flag: str):
    """``ID=DIR`` repeatable-flag parser shared by serve/serve-fleet.
    Returns a dict, or None after printing an error (ids ride the
    ``/m/<id>/`` routing prefix, so ``/`` and blanks are rejected)."""
    out = {}
    for kv in pairs:
        mid, sep, d = kv.partition("=")
        if not sep or not mid or not d or "/" in mid:
            print(
                f"error: {flag} expects ID=DIR with a /-free id, "
                f"got {kv!r}",
                file=sys.stderr,
            )
            return None
        out[mid] = d
    return out


def _run_serve_fleet(args) -> int:
    from glint_word2vec_tpu.fleet import (
        AutoscaleConfig, CanaryConfig, QosConfig, serve_fleet,
    )

    models = _parse_model_specs(args.add_model, "--add-model")
    if models is None:
        return 1
    model_watch_dirs = _parse_model_specs(
        args.watch_model, "--watch-model"
    )
    if model_watch_dirs is None:
        return 1
    if args.model is None and args.watch_checkpoint is None:
        print(
            "error: serve-fleet needs --model or --watch-checkpoint",
            file=sys.stderr,
        )
        return 1
    flags = [
        "--max-batch", str(args.max_batch),
        "--cache-size", str(args.cache_size),
        "--max-inflight", str(args.max_inflight),
        "--request-deadline", str(args.request_deadline),
        "--degraded-after", str(args.degraded_after),
    ]
    # (--watch-checkpoint/--watch-poll are NOT appended here: in
    # coordinated mode the rollout coordinator owns every swap, and in
    # --uncoordinated-watch mode the FleetSupervisor's replica argv
    # builder supplies both flags itself.)
    if args.ann:
        flags += [
            "--ann",
            "--ann-clusters", str(args.ann_clusters),
            "--ann-nprobe", str(args.ann_nprobe),
            "--ann-iters", str(args.ann_iters),
            "--ann-sample", str(args.ann_sample),
            "--ann-recall-gate", str(args.ann_recall_gate),
            "--ann-recall-sample", str(args.ann_recall_sample),
        ]
    replica0_env = {}
    for kv in args.replica0_env:
        if "=" not in kv:
            print(
                f"error: --replica0-env expects KEY=VAL, got {kv!r}",
                file=sys.stderr,
            )
            return 1
        k, v = kv.split("=", 1)
        replica0_env[k] = v
    canary = None
    if ((args.watch_checkpoint is not None or model_watch_dirs)
            and not args.no_canary
            and not args.uncoordinated_watch):
        probes = None
        if args.canary_probes:
            with open(args.canary_probes) as f:
                probes = json.load(f)
            if not isinstance(probes, list):
                print(
                    "error: --canary-probes must be a JSON list of "
                    '{"path", "body"} objects',
                    file=sys.stderr,
                )
                return 1
        canary = CanaryConfig(
            mirror_every=args.canary_mirror_every,
            min_scores=args.canary_min_scores,
            mirror_seconds=args.canary_mirror_seconds,
            agreement_gate=args.canary_agreement,
            top_k=args.canary_top_k,
            probes=probes,
        )
    qos = None
    if (args.qos_tenant_rate is not None
            or args.qos_bulk_max_inflight is not None):
        qos = QosConfig(
            tenant_rate=args.qos_tenant_rate,
            tenant_burst=args.qos_tenant_burst,
            bulk_max_inflight=args.qos_bulk_max_inflight,
        )
    autoscale = None
    if args.warm_spares > 0:
        autoscale = AutoscaleConfig(
            min_live=args.replicas,
            max_live=args.replicas + args.warm_spares,
            interval=args.autoscale_interval,
            up_shed_per_sec=args.autoscale_up_shed_rate,
            up_p95_ms=args.autoscale_up_p95_ms,
            up_window_seconds=args.autoscale_up_window,
            down_window_seconds=args.autoscale_down_window,
            cooldown_seconds=args.autoscale_cooldown,
        )
    return serve_fleet(
        args.model,
        replicas=args.replicas,
        host=args.host,
        port=args.port,
        watch_dir=args.watch_checkpoint,
        watch_poll=args.watch_poll,
        replica_flags=flags,
        log_dir=args.replica_log_dir,
        trace_dir=args.trace_dir,
        port_file=args.port_file,
        max_restarts=args.max_restarts,
        backoff_base_seconds=args.backoff_base,
        backoff_cap_seconds=args.backoff_cap,
        hang_kill_seconds=args.hang_kill_after,
        probe_interval=args.probe_interval,
        probe_timeout=args.probe_timeout,
        breaker_failures=args.breaker_failures,
        breaker_successes=args.breaker_successes,
        breaker_open_seconds=args.breaker_open_seconds,
        canary=canary,
        coordinated=not args.uncoordinated_watch,
        replica_env_first_launch=(
            {0: replica0_env} if replica0_env else None
        ),
        warm_spares=args.warm_spares,
        autoscale=autoscale,
        balancer_procs=args.balancer_procs,
        qos=qos,
        models=models,
        model_watch_dirs=model_watch_dirs,
        model_memory_budget=args.model_memory_budget,
    )


def _run_trace_merge(args) -> int:
    """``trace-merge``: jax-free stitcher over EventRecorder JSONL
    sinks — directories expand to their (rotated included) .jsonl
    files, the merged document is written atomically, and the summary
    line reports how many trace ids actually stitched across
    processes."""
    import glob
    import os

    from glint_word2vec_tpu.obs.aggregate import merge_trace_logs
    from glint_word2vec_tpu.utils import atomic_write_json

    paths = []
    for inp in args.inputs:
        if os.path.isdir(inp):
            paths += sorted(
                glob.glob(os.path.join(inp, "*.jsonl"))
                + glob.glob(os.path.join(inp, "*.jsonl.1"))
            )
        else:
            paths.append(inp)
    if not paths:
        print("error: no trace JSONL inputs found", file=sys.stderr)
        return 1
    doc = merge_trace_logs(paths)
    atomic_write_json(args.out, doc)
    other = doc["otherData"]
    print(json.dumps({
        "out": args.out,
        "events": len(doc["traceEvents"]),
        "trace_ids": other["trace_ids"],
        "stitched_traces": other["stitched_traces"],
        "sources": other["sources"],
    }))
    return 0


def _run(args) -> int:
    if args.cmd == "supervise":
        # Before force_platform/jax: the supervisor process never
        # touches a device.
        return _run_supervise(args)
    if args.cmd == "serve-fleet":
        # Likewise device-free: the balancer proxies; only the replica
        # SUBPROCESSES load tables.
        return _run_serve_fleet(args)
    if args.cmd == "fleet-shard":
        # One balancer data-plane shard: device-free like its parent.
        return _run_fleet_shard(args)
    if args.cmd == "trace-merge":
        # Pure file stitching: no devices, no model loads.
        return _run_trace_merge(args)
    if (args.cmd == "transform-file" and args.workers > 1
            and args.rank is None):
        # Rank-parallel bulk transform: the parent is a device-free
        # supervisor shell; only rank subprocesses load the tables.
        return _run_transform_fleet(args)

    from glint_word2vec_tpu.utils.platform import force_platform

    force_platform()  # a plain `JAX_PLATFORMS=cpu` must always work

    from glint_word2vec_tpu import FastTextWord2Vec, Word2Vec, load_model

    if args.cmd == "train":
        if args.coordinator or args.num_processes:
            from glint_word2vec_tpu.parallel import distributed as dist

            dist.initialize(
                coordinator_address=args.coordinator,
                num_processes=args.num_processes,
                process_id=args.process_id,
            )
        kw = dict(
            vector_size=args.vector_size,
            window=args.window,
            step_size=args.step_size,
            batch_size=args.batch_size,
            num_negatives=args.negatives,
            subsample_ratio=args.subsample_ratio,
            min_count=args.min_count,
            num_iterations=args.iterations,
            max_sentence_length=args.max_sentence_length,
            seed=args.seed,
            num_partitions=args.num_partitions,
            num_shards=args.num_shards,
            dtype=args.dtype,
            compute_dtype=args.compute_dtype,
            layout=args.layout,
            steps_per_call=args.steps_per_call,
            shared_negatives=args.shared_negatives,
            batch_packing=args.packing,
            exchange=args.exchange,
            exchange_capacity=args.exchange_capacity,
            exchange_wire=args.exchange_wire,
            exchange_every=args.exchange_every,
            exchange_topology=args.exchange_topology,
            exchange_shard=args.exchange_shard,
        )
        obs = None
        if (args.status_port is not None or args.status_file
                or args.event_log or args.chrome_trace
                or args.steptime_out or args.canary != "off"):
            from glint_word2vec_tpu.obs import ObsConfig

            obs = ObsConfig(
                event_log=args.event_log,
                event_capacity=args.event_capacity,
                chrome_trace=args.chrome_trace,
                status_port=args.status_port,
                status_host=args.status_host,
                status_file=args.status_file,
                canary=args.canary,
                canary_window=args.canary_window,
                canary_factor=args.canary_factor,
                canary_check_every=args.canary_check_every,
                steptime_path=args.steptime_out,
            )
        if args.fasttext:
            w2v = FastTextWord2Vec(
                **kw, obs=obs, min_n=args.min_n, max_n=args.max_n,
                bucket=args.bucket, max_subwords=args.max_subwords,
            )
        else:
            w2v = Word2Vec(**kw, obs=obs)
        # Streaming ingestion (fit_file): two passes over the file, flat
        # int32 encoding — never materializes Python sentence lists.
        model = w2v.fit_file(
            args.corpus, lowercase=args.lowercase,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every_epochs=args.checkpoint_every,
        )
        model.save(args.output)
        print(json.dumps({"saved": args.output, **(model.training_metrics or {})}))
        if args.metrics_out:
            from glint_word2vec_tpu.utils import atomic_write_json

            atomic_write_json(args.metrics_out, model.training_metrics)
        return 0

    if args.cmd == "fit-stream":
        return _run_fit_stream(args)

    if args.cmd == "serve":
        from glint_word2vec_tpu.serving import serve_model_dir

        if args.model is None and args.watch_checkpoint is None:
            print(
                "error: serve needs --model or --watch-checkpoint",
                file=sys.stderr,
            )
            return 1
        models = _parse_model_specs(args.add_model, "--add-model")
        if models is None:
            return 1
        serve_model_dir(
            args.model, host=args.host, port=args.port,
            max_batch=args.max_batch, warmup=not args.no_warmup,
            cache_size=args.cache_size,
            max_inflight=args.max_inflight,
            request_deadline=args.request_deadline,
            degraded_after=args.degraded_after,
            watch_dir=args.watch_checkpoint,
            watch_poll=args.watch_poll,
            port_file=args.port_file,
            trace_log=args.trace_log,
            flight_dir=args.flight_dir,
            models=models,
            model_memory_budget=args.model_memory_budget,
            watch_models=args.watch_models,
            **_ann_kwargs(args),
        )
        return 0

    model = load_model(args.model)
    if args.cmd == "transform-file":
        return _run_transform_file(args, model)
    if args.cmd == "synonyms-dump":
        return _run_synonyms_dump(args, model)
    if args.cmd == "synonyms":
        for w, s in model.find_synonyms(args.word, args.num):
            print(f"{w}\t{s:.4f}")
    elif args.cmd == "analogy":
        for w, s in model.analogy(args.positive, args.negative, args.num):
            print(f"{w}\t{s:.4f}")
    elif args.cmd == "transform":
        vec = model.transform_sentences([args.sentence.split()])[0]
        print(json.dumps([round(float(x), 6) for x in vec]))
    elif args.cmd == "eval":
        from glint_word2vec_tpu.eval import evaluate_analogies, parse_analogy_file

        questions = parse_analogy_file(
            args.questions, lowercase=not args.no_lowercase
        )
        result = evaluate_analogies(model, questions, top_k=args.top_k)
        print(json.dumps(result.to_dict()))
    elif args.cmd == "info":
        print(
            json.dumps(
                {
                    "family": type(model).__name__,
                    "vocab_size": model.vocab.size,
                    "vector_size": model.vector_size,
                    "train_words_count": model.vocab.train_words_count,
                    "params": json.loads(model.params.to_json()),
                }
            )
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
