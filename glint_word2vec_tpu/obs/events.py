"""Low-overhead span/event recording for run-wide observability.

The reference's entire training telemetry is one log line every 10k words
(mllib:399-413; SURVEY.md §5 "tracing: none"). This is the structured
replacement: instrumentation sites (the fit loops' phases, engine table
mutations, query-shape compiles) record spans and instant events into a
thread-safe bounded ring with an optional JSONL sink, and the ring
re-exports as a Chrome-trace (``chrome://tracing`` / Perfetto) JSON so
host spans can be eyeballed against the device xplane traces
``scripts/trace_summarize.py`` parses.

Two usage layers:

- ``EventRecorder`` — the recorder object a run owns (obs.ObsRun wires
  one per instrumented fit).
- Module-level ``emit(name, **args)`` / ``span(name, **args)`` — the
  process-wide hooks instrumentation sites call unconditionally. With no
  recorder installed they cost one global read (and ``span`` returns a
  shared no-op context manager), so the disabled path stays off the fit
  hot loop's profile.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Optional

logger = logging.getLogger(__name__)


class _NullSpan:
    """Shared no-op context manager returned when recording is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def update(self, **args) -> None:
        """No-op twin of :meth:`_Span.update`."""


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_rec", "_name", "_args", "_t0")

    def __init__(self, rec: "EventRecorder", name: str, args: dict):
        self._rec = rec
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._rec._record(self._name, "X", self._t0, t1 - self._t0, self._args)
        return False

    def update(self, **args) -> None:
        """Amend the span's attributes before it closes — for values only
        known mid-span (the packed train loop's live step count is read
        back from the device inside the span). Args are recorded at
        ``__exit__``, so updates land in the emitted event."""
        self._args.update(args)


class EventRecorder:
    """Thread-safe span/event log: the newest ``capacity`` events in a
    bounded ring (overflow counted in ``dropped``, never unbounded host
    memory) plus an optional JSONL sink that receives EVERY event.

    Event timestamps (``ts``, microseconds) run on a process-local
    monotonic clock anchored at recorder construction; ``wall_t0`` maps
    them back to the epoch for correlation with device traces. Span
    events use the Chrome-trace complete form (``ph: "X"`` with ``dur``),
    instants ``ph: "i"`` — each JSONL line IS a valid traceEvents entry,
    and :meth:`chrome_trace` wraps the ring into a full document.
    """

    def __init__(self, capacity: int = 65536,
                 jsonl_path: Optional[str] = None):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self.recorded = 0
        self.dropped = 0
        self.jsonl_path = jsonl_path
        self.wall_t0 = time.time()
        self._t0 = time.perf_counter()
        # graftlint: ignore[atomic-persist] streaming JSONL sink, not an artifact: a crash leaves a valid line-prefix that the merge/summarize tools accept
        self._sink = open(jsonl_path, "w") if jsonl_path else None
        if self._sink is not None:
            # Clock-anchor metadata line (Chrome-trace "M" event, ignored
            # by viewers): maps this recorder's monotonic ts=0 back to
            # the epoch, so scripts/trace_summarize.py --merge-ranks can
            # align per-rank JSONLs onto one shared timeline.
            try:
                self._sink.write(json.dumps({
                    "name": "clock_anchor", "ph": "M", "ts": 0,
                    "pid": os.getpid(),
                    "args": {"wall_t0": self.wall_t0},
                }) + "\n")
            except OSError as e:
                self._drop_sink_locked(e)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def _record(self, name: str, ph: str, t0: float, dur: float,
                args: dict) -> None:
        ev = {
            "name": name,
            "ph": ph,
            "ts": round((t0 - self._t0) * 1e6, 1),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if ph == "X":
            ev["dur"] = round(dur * 1e6, 1)
        else:
            ev["s"] = "t"  # instant scope: this thread
        if args:
            ev["args"] = args
        with self._mu:
            self.recorded += 1
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(ev)
            if self._sink is not None:
                try:
                    self._sink.write(json.dumps(ev) + "\n")
                except OSError as e:
                    # Observability must never take down the run it
                    # monitors: a dying sink (disk full, quota) degrades
                    # to ring-only recording.
                    self._drop_sink_locked(e)

    def event(self, name: str, **args) -> None:
        """Record one instant event."""
        self._record(name, "i", time.perf_counter(), 0.0, args)

    def span(self, name: str, **args) -> _Span:
        """Context manager recording one complete ("X") span on exit."""
        return _Span(self, name, args)

    def events(self) -> list:
        """Snapshot of the ring (oldest first)."""
        with self._mu:
            return list(self._ring)

    def counts(self) -> dict:
        with self._mu:
            return {
                "recorded": self.recorded,
                "dropped": self.dropped,
                "capacity": self._ring.maxlen,
            }

    # -- export --------------------------------------------------------

    def chrome_trace(self) -> dict:
        """The ring as a ``chrome://tracing`` / Perfetto JSON document."""
        events = self.events()
        with self._mu:
            dropped = self.dropped
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_t0": self.wall_t0,
                "dropped": dropped,
            },
        }

    def export_chrome_trace(self, path: str) -> None:
        from glint_word2vec_tpu.utils import atomic_write_json

        atomic_write_json(path, self.chrome_trace())

    def _drop_sink_locked(self, err) -> None:
        """Disable the JSONL sink after an I/O failure; caller holds
        the lock. Recording continues ring-only."""
        logger.warning(
            "event-log sink %s failed, continuing ring-only: %s",
            self.jsonl_path, err,
        )
        sink, self._sink = self._sink, None
        try:
            sink.close()
        except OSError:
            pass

    def flush(self) -> None:
        with self._mu:
            if self._sink is not None:
                try:
                    self._sink.flush()
                except OSError as e:
                    self._drop_sink_locked(e)

    def close(self) -> None:
        with self._mu:
            if self._sink is not None:
                try:
                    self._sink.flush()
                    self._sink.close()
                except OSError as e:
                    logger.warning(
                        "event-log sink %s failed at close: %s",
                        self.jsonl_path, e,
                    )
                self._sink = None


# ----------------------------------------------------------------------
# Process-wide current recorder: what engine-level instrumentation sites
# emit through without threading a recorder handle down every call path.
# ----------------------------------------------------------------------

_current: Optional[EventRecorder] = None


def set_recorder(rec: Optional[EventRecorder]) -> Optional[EventRecorder]:
    """Install the process-wide recorder (None disables). Returns ``rec``.
    One instrumented fit at a time is the supported shape; a second
    concurrent fit in the same process shares (or displaces) the
    recorder rather than corrupting anything."""
    global _current
    _current = rec
    return rec


def get_recorder() -> Optional[EventRecorder]:
    return _current


def emit(name: str, **args) -> None:
    """Instant event on the current recorder; no-op when recording is off."""
    rec = _current
    if rec is not None:
        rec.event(name, **args)


def span(name: str, **args):
    """Span on the current recorder; the shared no-op context manager
    when recording is off (the disabled path must cost ~nothing on the
    fit hot loop)."""
    rec = _current
    if rec is None:
        return NULL_SPAN
    return rec.span(name, **args)
