"""Low-overhead span/event recording for run-wide observability.

The reference's entire training telemetry is one log line every 10k words
(mllib:399-413; SURVEY.md §5 "tracing: none"). This is the structured
replacement: instrumentation sites (the fit loops' phases, engine table
mutations, query-shape compiles) record spans and instant events into a
thread-safe bounded ring with an optional JSONL sink, and the ring
re-exports as a Chrome-trace (``chrome://tracing`` / Perfetto) JSON so
host spans can be eyeballed against the device xplane traces
``scripts/trace_summarize.py`` parses.

Three usage layers:

- ``EventRecorder`` — the recorder object a run owns (obs.ObsRun wires
  one per instrumented fit).
- Module-level ``emit(name, **args)`` / ``span(name, **args)`` — the
  process-wide hooks instrumentation sites call unconditionally. With no
  recorder installed they cost one global read (and ``span`` returns a
  shared no-op context manager), so the disabled path stays off the fit
  hot loop's profile.
- ``RequestTrace`` (ISSUE 18) — the per-request phase-span buffer the
  serving data plane uses for distributed tracing: the balancer mints a
  trace id (:func:`mint_trace_id`), propagates it over the wire
  (``X-Glint-Trace``), and every hop buffers its request-path phase
  spans locally, flushing them into the ring only when the tail-based
  sampler keeps the request (always: errors, sheds, slow requests;
  1-in-N otherwise). Request-path span NAMES are drawn from the
  :data:`REQUEST_SPANS` registry — graftlint's span-registry rule
  rejects ad-hoc string literals at instrumentation sites.
"""

from __future__ import annotations

import atexit
import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Optional

logger = logging.getLogger(__name__)

#: The request-path span vocabulary (ISSUE 18). Every distributed-
#: tracing instrumentation site (``RequestTrace.phase`` /
#: ``RequestTrace.add_phase`` / module-level ``phase_span``) MUST name
#: its span with a literal key of this dict — graftlint's span-registry
#: checker statically enforces both directions (no ad-hoc names at
#: sites, no dead registry entries), so the trace-merge tooling and the
#: CI stitch assertions can rely on the vocabulary.
REQUEST_SPANS = {
    "req.accept": "per-process root span of one request hop "
                  "(balancer or replica handler, accept to response)",
    "req.admission": "admission gate: inflight-slot acquire or shed",
    "req.queue": "coalescer queue wait, enqueue to leader drain",
    "req.hop": "balancer -> replica proxy attempt (one per retry hop)",
    "req.dispatch": "warm-bucket device dispatch of one coalesced batch",
    "req.query": "engine query path (args carry mode=ann|exact)",
    "req.readback": "device result harvest / host materialization",
    "req.serialize": "response serialization + socket write",
}

#: Wire header carrying the trace id across the balancer -> replica hop.
TRACE_HEADER = "X-Glint-Trace"

#: Tail-sampling knobs: keep 1 in GLINT_TRACE_SAMPLE of the healthy/fast
#: requests; always keep errors (status >= 400, which covers sheds and
#: deadline 504s) and requests slower than GLINT_TRACE_SLOW_MS.
_TRACE_SAMPLE_EVERY = max(
    1, int(os.environ.get("GLINT_TRACE_SAMPLE") or 32)
)
_TRACE_SLOW_MS = float(os.environ.get("GLINT_TRACE_SLOW_MS") or 250.0)

#: Default JSONL sink rotation bound (satellite: a long traced run must
#: not grow the sink without limit). One rotated generation is kept
#: (``<path>.1``), so worst-case disk is ~2x this.
_SINK_MAX_BYTES = int(
    os.environ.get("GLINT_EVENT_SINK_MAX_BYTES") or 64 * 1024 * 1024
)

_sample_counter = itertools.count()


def mint_trace_id() -> str:
    """A 16-hex-char request trace id (no RNG seeding interplay: reads
    the OS entropy pool directly)."""
    return os.urandom(8).hex()


class _NullSpan:
    """Shared no-op context manager returned when recording is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def update(self, **args) -> None:
        """No-op twin of :meth:`_Span.update`."""


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_rec", "_name", "_args", "_t0")

    def __init__(self, rec: "EventRecorder", name: str, args: dict):
        self._rec = rec
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._rec._record(self._name, "X", self._t0, t1 - self._t0, self._args)
        return False

    def update(self, **args) -> None:
        """Amend the span's attributes before it closes — for values only
        known mid-span (the packed train loop's live step count is read
        back from the device inside the span). Args are recorded at
        ``__exit__``, so updates land in the emitted event."""
        self._args.update(args)


class _Phase:
    """Context manager buffering one request phase span into its
    :class:`RequestTrace` (not the ring — the tail sampler decides at
    ``finish`` whether the buffered spans are flushed at all)."""

    __slots__ = ("_tr", "_name", "_args", "_t0")

    def __init__(self, tr: "RequestTrace", name: str, args: dict):
        self._tr = tr
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tr._spans.append(
            (self._name, self._t0, t1 - self._t0, self._args)
        )
        return False

    def update(self, **args) -> None:
        self._args.update(args)


class NullRequestTrace:
    """Trace-id-carrying no-op returned when no recorder is installed:
    the id still propagates over the wire (a downstream hop may be
    recording even when this one is not), but nothing is buffered."""

    __slots__ = ("trace_id", "kept")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.kept = False

    def phase(self, name: str, **args):
        return NULL_SPAN

    def add_phase(self, name: str, t0: float, dur: float, **args) -> None:
        pass

    def finish(self, status: int = 200, *, force: bool = False) -> bool:
        return False


#: Shared stateless no-op trace for call sites whose caller passed no
#: trace at all (direct library use of the coalescer, tests).
NULL_TRACE = NullRequestTrace("")


class RequestTrace:
    """Per-request phase-span buffer with tail-based sampling.

    One hop's handler owns one instance (single-writer; cross-thread
    phases — the coalescer leader's queue/dispatch timestamps — are
    converted by the OWNING handler thread via :meth:`add_phase`).
    ``finish(status)`` applies the tail-sampling policy: errors/sheds
    (status >= 400), slow requests, and ``force`` are always kept;
    everything else is kept 1 in ``GLINT_TRACE_SAMPLE``. Kept spans are
    flushed into the recorder ring/sink with the trace id attached, so
    ``cli trace-merge`` can stitch hops across processes by id.
    """

    __slots__ = ("trace_id", "_rec", "_spans", "_t0", "kept")

    def __init__(self, trace_id: str, rec: "EventRecorder"):
        self.trace_id = trace_id
        self._rec = rec
        self._spans: list = []
        self._t0 = time.perf_counter()
        self.kept = False

    def phase(self, name: str, **args) -> _Phase:
        """Context manager buffering one registry-named phase span."""
        return _Phase(self, name, args)

    def add_phase(self, name: str, t0: float, dur: float, **args) -> None:
        """Buffer a phase from externally captured timestamps (the
        coalescer leader stamps perf_counter() pairs into the request
        dict; the waiter thread converts them here)."""
        self._spans.append((name, t0, dur, args))

    def finish(self, status: int = 200, *, force: bool = False) -> bool:
        """Apply tail sampling; flush buffered spans if kept. Returns
        whether the trace was kept (the caller can use that to attach
        an exemplar only for traces that actually exist in the ring)."""
        spans, self._spans = self._spans, []
        if not spans:
            return False
        slow = (time.perf_counter() - self._t0) * 1e3 >= _TRACE_SLOW_MS
        keep = (
            force or slow or int(status) >= 400
            or next(_sample_counter) % _TRACE_SAMPLE_EVERY == 0
        )
        if not keep:
            return False
        # The root span (req.accept closes last, so it is the final
        # buffered entry) carries the response status.
        spans[-1][3].setdefault("status", int(status))
        for name, t0, dur, args in spans:
            a = dict(args)
            a["trace"] = self.trace_id
            self._rec._record(name, "X", t0, dur, a)
        self.kept = True
        return True


def request_trace(trace_id: Optional[str] = None, rec=None):
    """Start one hop's request trace: adopts the propagated trace id (or
    mints one at the edge) and binds the process recorder. With no
    recorder installed this degrades to a :class:`NullRequestTrace` that
    still carries the id for onward propagation."""
    if rec is None:
        rec = _current
    tid = trace_id or mint_trace_id()
    if rec is None:
        return NullRequestTrace(tid)
    return RequestTrace(tid, rec)


class EventRecorder:
    """Thread-safe span/event log: the newest ``capacity`` events in a
    bounded ring (overflow counted in ``dropped``, never unbounded host
    memory) plus an optional JSONL sink that receives EVERY event.

    Event timestamps (``ts``, microseconds) run on a process-local
    monotonic clock anchored at recorder construction; the
    ``(mono_t0, wall_t0)`` pair recorded at creation (and emitted as the
    sink's ``clock_anchor`` metadata line) maps them back to the epoch,
    so multi-process merges align per-process timelines exactly. Span
    events use the Chrome-trace complete form (``ph: "X"`` with
    ``dur``), instants ``ph: "i"`` — each JSONL line IS a valid
    traceEvents entry, and :meth:`chrome_trace` wraps the ring into a
    full document.

    The sink is bounded: once it exceeds ``max_sink_bytes`` it rotates
    to ``<path>.1`` (one generation kept) and restarts with a fresh
    clock-anchor line, and an ``atexit`` flush makes sure an
    un-``close()``-d recorder still leaves complete lines behind.
    """

    def __init__(self, capacity: int = 65536,
                 jsonl_path: Optional[str] = None,
                 max_sink_bytes: Optional[int] = None):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self.recorded = 0
        self.dropped = 0
        self.jsonl_path = jsonl_path
        self.max_sink_bytes = int(
            max_sink_bytes if max_sink_bytes is not None
            else _SINK_MAX_BYTES
        )
        self.sink_rotations = 0
        self._sink_bytes = 0
        self.wall_t0 = time.time()
        self._t0 = time.perf_counter()
        # graftlint: ignore[atomic-persist] streaming JSONL sink, not an artifact: a crash leaves a valid line-prefix that the merge/summarize tools accept
        self._sink = open(jsonl_path, "w") if jsonl_path else None
        if self._sink is not None:
            self._write_anchor_locked()
            # Crash/exit hygiene: a run that never reaches close() (a
            # SIGTERMed replica, a test that leaks the recorder) still
            # flushes buffered lines. close() unregisters.
            atexit.register(self.flush)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    @property
    def mono_t0(self) -> float:
        """Monotonic half of the clock-anchor pair (``ts`` zero)."""
        return self._t0

    def _anchor_args(self) -> dict:
        args = {"wall_t0": self.wall_t0, "mono_t0": self._t0}
        # A supervisor-minted gang trace id (one per launch generation)
        # stitches this process's whole timeline to its gang.
        gang = os.environ.get("GLINT_TRACE_ID")
        if gang:
            args["trace"] = gang
        return args

    def _write_anchor_locked(self) -> None:
        """Clock-anchor metadata line (Chrome-trace "M" event, ignored
        by viewers): the (monotonic, wall) epoch pair mapping this
        recorder's ts=0 back to the epoch, so trace-merge tools can
        align per-process JSONLs onto one shared timeline."""
        try:
            line = json.dumps({
                "name": "clock_anchor", "ph": "M", "ts": 0,
                "pid": os.getpid(),
                "args": self._anchor_args(),
            }) + "\n"
            self._sink.write(line)
            self._sink_bytes += len(line)
        except OSError as e:
            self._drop_sink_locked(e)

    def _rotate_sink_locked(self) -> None:
        """Size-bounded sink: rotate the full file to ``<path>.1`` and
        restart (fresh anchor). One kept generation bounds disk at
        ~2x ``max_sink_bytes`` however long the traced run lives."""
        try:
            self._sink.flush()
            self._sink.close()
            os.replace(self.jsonl_path, self.jsonl_path + ".1")
            # graftlint: ignore[atomic-persist] streaming JSONL sink (see __init__)
            self._sink = open(self.jsonl_path, "w")
        except OSError as e:
            self._drop_sink_locked(e)
            return
        self._sink_bytes = 0
        self.sink_rotations += 1
        self._write_anchor_locked()

    def _record(self, name: str, ph: str, t0: float, dur: float,
                args: dict) -> None:
        ev = {
            "name": name,
            "ph": ph,
            "ts": round((t0 - self._t0) * 1e6, 1),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if ph == "X":
            ev["dur"] = round(dur * 1e6, 1)
        else:
            ev["s"] = "t"  # instant scope: this thread
        if args:
            ev["args"] = args
        with self._mu:
            self.recorded += 1
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(ev)
            if self._sink is not None:
                try:
                    line = json.dumps(ev) + "\n"
                    self._sink.write(line)
                    self._sink_bytes += len(line)
                except OSError as e:
                    # Observability must never take down the run it
                    # monitors: a dying sink (disk full, quota) degrades
                    # to ring-only recording.
                    self._drop_sink_locked(e)
                    return
                if self._sink_bytes >= self.max_sink_bytes:
                    self._rotate_sink_locked()

    def event(self, name: str, **args) -> None:
        """Record one instant event."""
        self._record(name, "i", time.perf_counter(), 0.0, args)

    def span(self, name: str, **args) -> _Span:
        """Context manager recording one complete ("X") span on exit."""
        return _Span(self, name, args)

    def events(self) -> list:
        """Snapshot of the ring (oldest first)."""
        with self._mu:
            return list(self._ring)

    def recent_events(self, seconds: float) -> list:
        """Ring events whose span/instant started within the last
        ``seconds`` — the flight recorder's bundle window."""
        cutoff = (time.perf_counter() - self._t0 - seconds) * 1e6
        with self._mu:
            return [e for e in self._ring if e.get("ts", 0.0) >= cutoff]

    def counts(self) -> dict:
        with self._mu:
            return {
                "recorded": self.recorded,
                "dropped": self.dropped,
                "capacity": self._ring.maxlen,
            }

    # -- export --------------------------------------------------------

    def chrome_trace(self) -> dict:
        """The ring as a ``chrome://tracing`` / Perfetto JSON document."""
        events = self.events()
        with self._mu:
            dropped = self.dropped
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_t0": self.wall_t0,
                "mono_t0": self._t0,
                "dropped": dropped,
            },
        }

    def export_chrome_trace(self, path: str) -> None:
        from glint_word2vec_tpu.utils import atomic_write_json

        atomic_write_json(path, self.chrome_trace())

    def _drop_sink_locked(self, err) -> None:
        """Disable the JSONL sink after an I/O failure; caller holds
        the lock. Recording continues ring-only."""
        logger.warning(
            "event-log sink %s failed, continuing ring-only: %s",
            self.jsonl_path, err,
        )
        sink, self._sink = self._sink, None
        try:
            sink.close()
        except OSError:
            pass

    def flush(self) -> None:
        with self._mu:
            if self._sink is not None:
                try:
                    self._sink.flush()
                except OSError as e:
                    self._drop_sink_locked(e)

    def close(self) -> None:
        with self._mu:
            if self._sink is not None:
                try:
                    self._sink.flush()
                    self._sink.close()
                except OSError as e:
                    logger.warning(
                        "event-log sink %s failed at close: %s",
                        self.jsonl_path, e,
                    )
                self._sink = None
                try:
                    atexit.unregister(self.flush)
                except Exception:  # pragma: no cover - interpreter teardown
                    pass


# ----------------------------------------------------------------------
# Process-wide current recorder: what engine-level instrumentation sites
# emit through without threading a recorder handle down every call path.
# ----------------------------------------------------------------------

_current: Optional[EventRecorder] = None


def set_recorder(rec: Optional[EventRecorder]) -> Optional[EventRecorder]:
    """Install the process-wide recorder (None disables). Returns ``rec``.
    One instrumented fit at a time is the supported shape; a second
    concurrent fit in the same process shares (or displaces) the
    recorder rather than corrupting anything."""
    global _current
    _current = rec
    return rec


def get_recorder() -> Optional[EventRecorder]:
    return _current


def emit(name: str, **args) -> None:
    """Instant event on the current recorder; no-op when recording is off."""
    rec = _current
    if rec is not None:
        rec.event(name, **args)


def span(name: str, **args):
    """Span on the current recorder; the shared no-op context manager
    when recording is off (the disabled path must cost ~nothing on the
    fit hot loop)."""
    rec = _current
    if rec is None:
        return NULL_SPAN
    return rec.span(name, **args)


def phase_span(name: str, **args):
    """Request-path span recorded DIRECTLY into the ring (never
    tail-sampled away): the coalescer leader's device-dispatch lane uses
    this so the stitched trace always shows the batch a kept request
    rode in. ``name`` must be a :data:`REQUEST_SPANS` literal —
    graftlint's span-registry rule checks call sites statically."""
    rec = _current
    if rec is None:
        return NULL_SPAN
    return rec.span(name, **args)
