"""Fleet-level observability: merge per-rank snapshots into ONE gang view.

PR 3 gave every *process* a heartbeat and a Prometheus endpoint; PR 7
made the trainer a supervised multi-process gang — but nothing saw the
gang as one run. This module is that layer (ISSUE 8):

- :func:`merge_training_snapshots` folds the per-rank heartbeat
  snapshots (the ``--status-file`` JSON each worker already writes) into
  one gang document: summed counters, total words/sec, per-rank
  progress, a straggler-skew gauge (``rank_skew`` = max/median of the
  per-rank mean step time — the signal that dominates distributed SGNS
  scaling, Ji et al. arXiv:1604.04661), and the step-time attribution
  ledger merged across ranks (exact histogram-bucket merges via
  :meth:`LatencyHistogram.merge`).
- :func:`merge_serving_snapshots` does the same for serving replicas:
  endpoint latency histograms merge bucket-exactly, counters sum — the
  result has the exact shape of one ``ServingMetrics.snapshot`` so the
  existing Prometheus renderer serves a whole replica fleet unchanged.
- :class:`GangStatusServer` is the HTTP face the supervisor parks next
  to its liveness loop: one merged ``/metrics`` (JSON +
  ``?format=prometheus``) and ``/healthz`` for the whole gang,
  generation-stamped so a scrape during a restart can never mix
  pre-restart ranks into the current gang's view. Serving processes
  join the aggregate by URL (``serving_urls``): their JSON snapshots
  are scraped lazily per request, merged, and appended to the gang
  exposition.

Everything here is jax-free on purpose: it runs in the supervisor
process, which never touches a device.
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, List, Optional
from urllib.parse import parse_qs, urlparse

from glint_word2vec_tpu.obs.slo import merge_slo_snapshots
from glint_word2vec_tpu.utils.metrics import LEDGER_PHASES, LatencyHistogram

logger = logging.getLogger(__name__)

#: Heartbeat-snapshot keys summed into the gang ``counters`` block; the
#: merged value of each is BY CONSTRUCTION the sum of the per-rank
#: values (the acceptance contract a fleet dashboard can rely on).
_SUM_COUNTERS = (
    ("steps_total", "step"),
    ("words_done_total", "words_done"),
    ("query_compiles_total", "query_compiles"),
    ("async_save_waits_total", "async_save_waits"),
    # Replica-exchange + shard-checkpoint rollups (ISSUE 15): the gang
    # totals are the pod's bytes-on-wire / touched-row / spill budget.
    ("exchange_bytes_total", "exchange_bytes_total"),
    ("exchange_rows_total", "exchange_rows_total"),
    ("exchange_overflow_total", "exchange_overflow_total"),
    # ISSUE 16 wire-layer rollups: coalesced dispatch groups and the
    # per-hop byte split of the two-level topology.
    ("exchange_groups_total", "exchange_groups_total"),
    ("exchange_intra_bytes_total", "exchange_intra_bytes_total"),
    ("exchange_inter_bytes_total", "exchange_inter_bytes_total"),
    ("checkpoint_shards_skipped_total", "checkpoint_shards_skipped"),
)

#: Rank states that make the whole gang unhealthy on /healthz.
_BAD_STATES = ("diverged", "failed", "unhealthy")


def _mean_step_seconds(snap: dict) -> Optional[float]:
    steps = snap.get("step") or 0
    st = snap.get("step_time")
    if not steps or st is None:
        return None
    return float(st) / float(steps)


def merge_training_snapshots(
    snaps: Dict[int, Optional[dict]],
    *,
    generation: Optional[int] = None,
    num_workers: Optional[int] = None,
) -> dict:
    """One gang-level document from per-rank heartbeat snapshots.

    ``snaps`` maps rank -> snapshot (None = that rank has produced no
    current-generation heartbeat yet). Snapshots stamped with a
    different ``supervisor_generation`` than ``generation`` are dropped
    here as a second line of defense (the supervisor's reader already
    filters) — a pre-restart scrape must never pollute the merged view.
    """
    live: Dict[int, dict] = {}
    for rank, snap in snaps.items():
        if snap is None:
            continue
        gen = snap.get("supervisor_generation")
        if generation is not None and gen is not None and int(gen) != generation:
            continue
        live[int(rank)] = snap

    counters = {out: 0 for out, _ in _SUM_COUNTERS}
    counters["canary_trips_total"] = 0
    counters["events_recorded_total"] = 0
    counters["events_dropped_total"] = 0
    # Shard-checkpoint seconds fold to the SLOWEST rank (the actionable
    # fleet number, same policy as the serving aggregate's checkpoint
    # block); None until any rank reports them.
    shard_write_max = None
    shard_verify_max = None
    transform_ranks: List[dict] = []
    slo_snaps: List[dict] = []
    steptime_trace_id = None
    per_rank: Dict[str, dict] = {}
    wps_total = 0.0
    step_means: List[float] = []
    phase_acc = {
        p: {"seconds": 0.0, "count": 0, "hists": []} for p in LEDGER_PHASES
    }
    states = []
    for rank in sorted(live):
        snap = live[rank]
        states.append(snap.get("state", "unknown"))
        for out, key in _SUM_COUNTERS:
            counters[out] += int(snap.get(key) or 0)
        counters["canary_trips_total"] += int(
            (snap.get("canary") or {}).get("trips") or 0
        )
        ev = snap.get("events") or {}
        counters["events_recorded_total"] += int(ev.get("recorded") or 0)
        counters["events_dropped_total"] += int(ev.get("dropped") or 0)
        v = snap.get("checkpoint_shard_write_seconds")
        if v is not None:
            shard_write_max = max(shard_write_max or 0.0, v)
        v = snap.get("checkpoint_shard_verify_seconds")
        if v is not None:
            shard_verify_max = max(shard_verify_max or 0.0, v)
        tr = snap.get("transform")
        if tr:
            transform_ranks.append(tr)
        if snap.get("slo"):
            slo_snaps.append(snap["slo"])
        if steptime_trace_id is None:
            # First rank carrying a gang trace id wins: the supervisor
            # mints ONE id per launch generation, so any rank's is the
            # gang's (the steptime summary's exemplar anchor).
            steptime_trace_id = (
                (snap.get("steptime") or {}).get("trace_id")
            )
        wps = float(snap.get("words_per_sec_rolling") or 0.0)
        wps_total += wps
        ms = _mean_step_seconds(snap)
        if ms is not None:
            step_means.append(ms)
        per_rank[str(rank)] = {
            "state": snap.get("state"),
            "epoch": snap.get("epoch"),
            "step": snap.get("step") or 0,
            "words_done": snap.get("words_done") or 0,
            "words_per_sec_rolling": wps,
            "mean_step_seconds": (
                round(ms, 6) if ms is not None else None
            ),
            "host_frac": snap.get("host_frac"),
            "last_loss": snap.get("last_loss"),
            "uptime_seconds": snap.get("uptime_seconds"),
            "device_stall_seconds": snap.get("device_stall_seconds"),
        }
        st = (snap.get("steptime") or {}).get("phases") or {}
        for p in LEDGER_PHASES:
            info = st.get(p)
            if not info:
                continue
            phase_acc[p]["seconds"] += float(info.get("seconds") or 0.0)
            phase_acc[p]["count"] += int(info.get("count") or 0)
            if info.get("hist"):
                phase_acc[p]["hists"].append(info["hist"])

    # Straggler skew: the slowest rank's mean step time over the gang
    # median. 1.0 = perfectly balanced; the gauge the ROADMAP's
    # pod-scale item watches. None until at least one rank reports step
    # timing (rendered NaN in the Prometheus exposition).
    rank_skew = None
    if step_means:
        med = statistics.median(step_means)
        if med > 0:
            rank_skew = round(max(step_means) / med, 4)

    if not states:
        gang_state = "starting"
    elif any(s in _BAD_STATES for s in states):
        gang_state = next(s for s in states if s in _BAD_STATES)
    elif all(s == "done" for s in states):
        gang_state = "done"
    else:
        gang_state = "running"

    steptime = {}
    for p in LEDGER_PHASES:
        acc = phase_acc[p]
        entry = {
            "seconds": round(acc["seconds"], 4),
            "count": acc["count"],
        }
        if acc["hists"]:
            h = LatencyHistogram.merge(acc["hists"])
            entry.update(
                p50_ms=round(h.quantile(0.50) * 1e3, 3),
                p95_ms=round(h.quantile(0.95) * 1e3, 3),
                p99_ms=round(h.quantile(0.99) * 1e3, 3),
                # Accounted-span seconds only (the histogram's own
                # total): for "other" this EXCLUDES the folded
                # unattributed gap, so the Prometheus summary's _sum,
                # _count, and quantiles describe the same population.
                span_seconds=round(h.total, 4),
            )
        steptime[p] = entry

    # Bulk-transform rollup (ISSUE 17): ranks are embarrassingly
    # parallel over contiguous input spans, so counters sum, the fill
    # gauge folds to the WORST (sparsest) rank, and producer wait to
    # the slowest — the straggler-first policy the checkpoint seconds
    # above use.
    transform = None
    if transform_ranks:
        fills = [
            t.get("bucket_fill") for t in transform_ranks
            if t.get("bucket_fill") is not None
        ]
        transform = {
            "sentences_done_total": sum(
                int(t.get("sentences_done_total") or 0)
                for t in transform_ranks
            ),
            "input_sentences": sum(
                int(t.get("input_sentences") or 0)
                for t in transform_ranks
            ),
            "sentences_per_sec_total": round(sum(
                float(t.get("sentences_per_sec") or 0.0)
                for t in transform_ranks
            ), 1),
            "shards_committed_total": sum(
                int(t.get("shards_committed_total") or 0)
                for t in transform_ranks
            ),
            "shards_skipped_total": sum(
                int(t.get("shards_skipped_total") or 0)
                for t in transform_ranks
            ),
            "post_warmup_compiles_total": sum(
                int(t.get("post_warmup_compiles_total") or 0)
                for t in transform_ranks
            ),
            "bucket_fill_min": min(fills) if fills else None,
            "producer_wait_seconds_max": max(
                float(t.get("producer_wait_seconds") or 0.0)
                for t in transform_ranks
            ),
        }

    out = {
        "generation": generation,
        "num_workers": (
            num_workers if num_workers is not None else len(snaps)
        ),
        "ranks_reporting": len(live),
        "state": gang_state,
        "counters": counters,
        "words_per_sec_total": round(wps_total, 1),
        "rank_skew": rank_skew,
        "checkpoint_shard_write_seconds_max": shard_write_max,
        "checkpoint_shard_verify_seconds_max": shard_verify_max,
        "per_rank": per_rank,
        "steptime": steptime,
        "steptime_trace_id": steptime_trace_id,
    }
    if transform is not None:
        out["transform"] = transform
    slo = merge_slo_snapshots(slo_snaps)
    if slo is not None:
        out["slo"] = slo
    return out


def merge_serving_snapshots(snaps: Iterable[dict]) -> Optional[dict]:
    """Merge serving-replica ``ServingMetrics.snapshot`` documents into
    one with the identical shape, so ``serving_to_prometheus`` renders a
    whole replica fleet with no second code path. Latency histograms
    merge bucket-exactly when snapshots carry ``hist`` state (this
    repo's do); a hist-less legacy snapshot degrades that endpoint's
    quantiles to the max across replicas (conservative, flagged via
    ``"approx": true``). Returns None for an empty input."""
    snaps = [s for s in snaps if s]
    if not snaps:
        return None
    endpoints: Dict[str, dict] = {}
    ep_hists: Dict[str, list] = {}
    for s in snaps:
        for path, ep in (s.get("endpoints") or {}).items():
            agg = endpoints.setdefault(path, {
                "count": 0, "errors": 0, "p50_ms": 0.0, "p95_ms": 0.0,
                "p99_ms": 0.0, "mean_ms": 0.0, "max_ms": 0.0,
                "_total_ms": 0.0,
            })
            agg["count"] += int(ep.get("count") or 0)
            agg["errors"] += int(ep.get("errors") or 0)
            agg["max_ms"] = max(agg["max_ms"], float(ep.get("max_ms") or 0.0))
            agg["_total_ms"] += (
                float(ep.get("mean_ms") or 0.0) * int(ep.get("count") or 0)
            )
            # Max-fold EVERY replica's quantiles (the approx fallback
            # must cover the whole fleet, hist-carrying replicas
            # included — dropping a slow replica's p99 because its peer
            # is legacy would hide the straggler); the exact merge
            # below overwrites when every replica carried hist state.
            for q in ("p50_ms", "p95_ms", "p99_ms"):
                agg[q] = max(agg[q], float(ep.get(q) or 0.0))
            if ep.get("hist"):
                ep_hists.setdefault(path, []).append(ep["hist"])
            else:
                agg["approx"] = True
    for path, agg in endpoints.items():
        hists = ep_hists.get(path)
        if hists and not agg.get("approx"):
            h = LatencyHistogram.merge(hists)
            agg.update(
                p50_ms=round(h.quantile(0.50) * 1e3, 3),
                p95_ms=round(h.quantile(0.95) * 1e3, 3),
                p99_ms=round(h.quantile(0.99) * 1e3, 3),
                hist=h.state(),
            )
        agg["mean_ms"] = round(
            agg.pop("_total_ms") / max(agg["count"], 1), 3
        )

    batches: Dict[str, int] = {}
    cache = {"hits": 0, "misses": 0}
    over = {
        "shed_admission_total": 0, "shed_degraded_total": 0,
        "deadline_504_total": 0, "degraded_entered_total": 0,
        "inflight_peak": 0,
    }
    compiles = {"total": 0, "warmup": 0, "post_warmup": 0}
    ck = {
        "pending_async_saves": 0,
        "last_checkpoint_age_seconds": None,
        "checkpoint_write_seconds": None,
    }
    # Hot-swap block (ISSUES 10+14): counters sum; the swap age folds
    # to the STALEST replica (the one a rolling rollout left behind is
    # the actionable number); the generation is the fleet's only when
    # every replica serves the same one — "mixed" is itself signal (a
    # rollout in flight, or a halted one).
    swap = {
        "table_swaps_total": 0,
        "swap_failures_total": 0,
        "watch_errors_total": 0,
        "last_swap_age_seconds": None,
        "generation": None,
    }
    swap_gens = set()
    # ANN index block (ISSUE 12): counters sum; the recall and its
    # gate fold to the WORST replica (min recall, all-gates-pass) —
    # the actionable fleet numbers; ages/staleness fold to the
    # stalest.
    index = {
        "enabled": False,
        "replicas_with_index": 0,
        "clusters": None,
        "member_slots": None,
        "nprobe": None,
        "build_seconds": None,
        "last_refresh_age_seconds": None,
        "refreshes_total": 0,
        "recall_at10": None,
        "recall_gate_ok": None,
        "recall_gate_threshold": None,
        "ann_queries_total": 0,
        "probes_total": 0,
        "probes_per_query": None,
        "exact_fallbacks": {},
        "table_versions_behind": None,
    }
    for s in snaps:
        for size, n in (s.get("coalesced_batch_sizes") or {}).items():
            batches[size] = batches.get(size, 0) + int(n)
        c = s.get("synonym_cache") or {}
        cache["hits"] += int(c.get("hits") or 0)
        cache["misses"] += int(c.get("misses") or 0)
        o = s.get("overload") or {}
        for k in over:
            v = int(o.get(k) or 0)
            if k == "inflight_peak":
                over[k] = max(over[k], v)
            else:
                over[k] += v
        comp = s.get("compiles") or {}
        for k in compiles:
            compiles[k] += int(comp.get(k) or 0)
        hs = s.get("hot_swap") or {}
        for k in ("table_swaps_total", "swap_failures_total",
                  "watch_errors_total"):
            swap[k] += int(hs.get(k) or 0)
        v = hs.get("last_swap_age_seconds")
        if v is not None:
            swap["last_swap_age_seconds"] = (
                v if swap["last_swap_age_seconds"] is None
                else max(swap["last_swap_age_seconds"], v)
            )
        swap_gens.add(hs.get("generation"))
        sck = s.get("checkpoint") or {}
        ck["pending_async_saves"] += int(sck.get("pending_async_saves") or 0)
        for k in ("last_checkpoint_age_seconds",
                  "checkpoint_write_seconds"):
            v = sck.get(k)
            if v is not None:
                # Worst (largest) across replicas: the stalest
                # checkpoint and the slowest write are the actionable
                # fleet numbers.
                ck[k] = v if ck[k] is None else max(ck[k], v)
        si = s.get("index") or {}
        if si.get("enabled"):
            index["enabled"] = True
            index["replicas_with_index"] += 1
            for k in ("clusters", "member_slots", "nprobe",
                      "recall_gate_threshold"):
                if si.get(k) is not None:
                    index[k] = si[k]
            index["refreshes_total"] += int(si.get("refreshes_total") or 0)
            index["ann_queries_total"] += int(
                si.get("ann_queries_total") or 0
            )
            index["probes_total"] += int(si.get("probes_total") or 0)
            for reason, n in (si.get("exact_fallbacks") or {}).items():
                index["exact_fallbacks"][reason] = (
                    index["exact_fallbacks"].get(reason, 0) + int(n)
                )
            r = si.get("recall_at10")
            if r is not None:
                index["recall_at10"] = (
                    r if index["recall_at10"] is None
                    else min(index["recall_at10"], r)
                )
            g = si.get("recall_gate_ok")
            if g is not None:
                index["recall_gate_ok"] = (
                    bool(g) if index["recall_gate_ok"] is None
                    else (index["recall_gate_ok"] and bool(g))
                )
            for k in ("build_seconds", "last_refresh_age_seconds",
                      "table_versions_behind"):
                v = si.get(k)
                if v is not None:
                    index[k] = (
                        v if index[k] is None else max(index[k], v)
                    )
    if index["ann_queries_total"]:
        index["probes_per_query"] = round(
            index["probes_total"] / index["ann_queries_total"], 2
        )
    if len(swap_gens) == 1:
        swap["generation"] = next(iter(swap_gens))
    elif swap_gens:
        swap["generation"] = "mixed"
    # Per-model blocks (ISSUE 20): group each catalog model's
    # snapshots by id across replicas and fold every group through
    # THIS function (a per-model snapshot nests no further models, so
    # the recursion is exactly one level deep). The residency extras
    # fold additively — "resident_replicas" becomes the fleet-wide
    # count of replicas holding that model's tables on device.
    by_model: Dict[str, list] = {}
    for s in snaps:
        for mid, ms in (s.get("models") or {}).items():
            by_model.setdefault(mid, []).append(ms)
    models = {}
    for mid in sorted(by_model):
        group = by_model[mid]
        m = merge_serving_snapshots(group)
        m["model_id"] = mid
        m["resident_replicas"] = sum(
            int(g.get("resident_replicas") or 0) for g in group
        )
        m["resident"] = m["resident_replicas"] > 0
        m["pinned"] = any(g.get("pinned") for g in group)
        for k in ("resident_bytes", "stage_ins_total",
                  "evictions_total"):
            m[k] = sum(int(g.get(k) or 0) for g in group)
        models[mid] = m
    # Catalog block: LRU churn counters sum across replicas; the
    # membership/budget numbers are per-replica configuration, folded
    # to the max so a heterogeneous fleet surfaces its largest shape.
    catalog = None
    cat_snaps = [s.get("catalog") for s in snaps if s.get("catalog")]
    if cat_snaps:
        catalog = {
            "replicas": len(cat_snaps),
            "default_model": cat_snaps[0].get("default_model"),
            "models": max(
                int(c.get("models") or 0) for c in cat_snaps
            ),
            "resident_models": sum(
                int(c.get("resident_models") or 0) for c in cat_snaps
            ),
            "budget_bytes": max(
                (int(c["budget_bytes"]) for c in cat_snaps
                 if c.get("budget_bytes") is not None),
                default=None,
            ),
            "stage_in_seconds_total": round(sum(
                float(c.get("stage_in_seconds_total") or 0.0)
                for c in cat_snaps
            ), 3),
        }
        for k in ("evictions_total", "stage_ins_total",
                  "cold_hits_total", "resident_bytes",
                  "query_program_builds", "shared_program_hits"):
            catalog[k] = sum(int(c.get(k) or 0) for c in cat_snaps)
    out = {
        "replicas": len(snaps),
        "endpoints": {p: endpoints[p] for p in sorted(endpoints)},
        "coalesced_batch_sizes": {
            k: batches[k] for k in sorted(batches, key=int)
        },
        "synonym_cache": cache,
        "overload": over,
        "compiles": compiles,
        "hot_swap": swap,
        "checkpoint": ck,
        "index": index,
        # Fleet SLO view (ISSUE 18): window counts sum exactly, burn
        # rates re-derived from the sums — a replica restart never
        # corrupts the merged error budget.
        "slo": merge_slo_snapshots([s.get("slo") for s in snaps]),
    }
    if models:
        out["models"] = models
    if catalog is not None:
        out["catalog"] = catalog
    return out


def merge_trace_logs(paths: Iterable[str]) -> dict:
    """Stitch per-process ``EventRecorder`` JSONL rings into ONE
    clock-anchored Chrome-trace / Perfetto document (ISSUE 18).

    Each sink's events carry ``ts`` microseconds on that process's OWN
    monotonic clock; its leading ``clock_anchor`` metadata line records
    the ``(mono_t0, wall_t0)`` pair mapping ts=0 back to the epoch.
    The merge rebases every file onto the earliest ``wall_t0`` across
    inputs, so spans from the balancer, every replica, and a training
    gang land on one shared timeline — a stitched request reads
    balancer ``req.accept`` -> replica ``req.accept`` ->
    ``req.dispatch`` left to right in Perfetto.

    Per-process lanes are named after the source file
    (``process_name`` metadata); a missing file or a torn trailing
    line (a crash mid-write) is skipped and reported in
    ``otherData.sources``, never fatal. ``otherData.stitched_traces``
    counts trace ids seen in more than one process — the CI smoke
    asserts it is nonzero for a traced fleet.
    """
    events: List[dict] = []
    sources: Dict[str, str] = {}
    anchors: Dict[str, dict] = {}
    per_file: List[tuple] = []
    base_wall = None
    for path in paths:
        name = os.path.basename(path)
        if name.endswith(".jsonl"):
            name = name[: -len(".jsonl")]
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError as e:
            sources[str(path)] = f"error: {e}"
            continue
        anchor = None
        evs = []
        bad = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                bad += 1  # torn trailing line from a crashed writer
                continue
            if ev.get("name") == "clock_anchor" and ev.get("ph") == "M":
                if anchor is None:
                    anchor = ev.get("args") or {}
                continue
            evs.append(ev)
        if anchor is None or anchor.get("wall_t0") is None:
            sources[str(path)] = "error: no clock_anchor line"
            continue
        sources[str(path)] = (
            "ok" if not bad else f"ok ({bad} torn line(s) skipped)"
        )
        anchors[name] = anchor
        wall = float(anchor["wall_t0"])
        base_wall = wall if base_wall is None else min(base_wall, wall)
        per_file.append((name, wall, evs))
    trace_pids: Dict[str, set] = {}
    for name, wall, evs in per_file:
        offset_us = (wall - base_wall) * 1e6
        pid = evs[0].get("pid") if evs else None
        for ev in evs:
            ev = dict(ev)
            ev["ts"] = round(float(ev.get("ts") or 0.0) + offset_us, 1)
            events.append(ev)
            tid = (ev.get("args") or {}).get("trace")
            if tid:
                trace_pids.setdefault(tid, set()).add(name)
        if pid is not None:
            events.append({
                "name": "process_name", "ph": "M", "ts": 0,
                "pid": pid, "args": {"name": name},
            })
    events.sort(key=lambda e: (e.get("ts") or 0.0))
    stitched = sum(1 for pids in trace_pids.values() if len(pids) > 1)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "wall_t0": base_wall,
            "sources": sources,
            "anchors": anchors,
            "trace_ids": len(trace_pids),
            "stitched_traces": stitched,
        },
    }


class GangStatusServer:
    """Merged gang ``/metrics`` + ``/healthz`` for the supervisor.

    The supervisor feeds it each liveness sweep via :meth:`update`
    (generation + per-rank snapshots); requests serve the merge of the
    latest sweep. ``serving_urls`` are scraped lazily per request (2s
    timeout each, failures reported in ``serving_sources`` instead of
    failing the scrape) and merged into a single serving section.

    Routes:
      GET /healthz                   -> gang state (200, or 503 when any
                                        rank is diverged/failed/unhealthy)
      GET /metrics                   -> merged gang JSON (+ "serving")
      GET /metrics?format=prometheus -> gang exposition, serving fleet
                                        exposition appended when joined
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 num_workers: int = 1,
                 serving_urls: Optional[List[str]] = None):
        from glint_word2vec_tpu.obs.prometheus import (
            gang_to_prometheus,
            serving_to_prometheus,
        )

        self.num_workers = int(num_workers)
        self.serving_urls = list(serving_urls or [])
        self._mu = threading.Lock()
        self._generation = 0
        self._snaps: Dict[int, Optional[dict]] = {}
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("gang-metrics: " + fmt, *args)

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urlparse(self.path)
                if url.path == "/healthz":
                    merged = server.merged(include_serving=False)
                    ok = merged["state"] not in _BAD_STATES
                    body = json.dumps({
                        "status": "ok" if ok else merged["state"],
                        "state": merged["state"],
                        "generation": merged["generation"],
                        "num_workers": merged["num_workers"],
                        "ranks_reporting": merged["ranks_reporting"],
                        "words_per_sec_total":
                            merged["words_per_sec_total"],
                        "rank_skew": merged["rank_skew"],
                    }).encode()
                    self._send(200 if ok else 503, body,
                               "application/json")
                elif url.path == "/metrics":
                    merged = server.merged()
                    fmt = parse_qs(url.query).get("format", ["json"])[0]
                    if fmt == "prometheus":
                        text = gang_to_prometheus(merged)
                        if merged.get("serving"):
                            text += serving_to_prometheus(
                                merged["serving"]
                            )
                        self._send(
                            200, text.encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    else:
                        self._send(200, json.dumps(merged).encode(),
                                   "application/json")
                else:
                    self._send(
                        404,
                        json.dumps(
                            {"error": f"no route {url.path}"}
                        ).encode(),
                        "application/json",
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -- supervisor-facing ---------------------------------------------

    def update(self, generation: int,
               snaps: Dict[int, Optional[dict]]) -> None:
        """Install the latest liveness sweep's per-rank snapshots. The
        generation stamps the merged view; snapshots the supervisor
        read are already generation-filtered."""
        with self._mu:
            self._generation = int(generation)
            self._snaps = dict(snaps)

    def merged(self, include_serving: bool = True) -> dict:
        with self._mu:
            gen, snaps = self._generation, dict(self._snaps)
        merged = merge_training_snapshots(
            snaps, generation=gen, num_workers=self.num_workers
        )
        if include_serving and self.serving_urls:
            serving, sources = self._scrape_serving()
            merged["serving"] = serving
            merged["serving_sources"] = sources
            # Lift the scraped replicas' merged SLO view next to any
            # training-rank objectives so the gang exposition renders
            # one glint_gang_slo_* family set for the whole deployment.
            slo = merge_slo_snapshots([
                merged.get("slo"), (serving or {}).get("slo"),
            ])
            if slo is not None:
                merged["slo"] = slo
        return merged

    def _scrape_serving(self):
        """Fetch each joined serving replica's JSON /metrics snapshot.
        A dead replica is reported, never fatal — the gang view must
        stay up while a replica restarts."""
        snaps, sources = [], {}
        for url in self.serving_urls:
            try:
                with urllib.request.urlopen(url, timeout=2.0) as r:
                    snaps.append(json.loads(r.read().decode()))
                sources[url] = "ok"
            except Exception as e:  # URLError, timeout, bad JSON
                sources[url] = f"error: {e}"
        return merge_serving_snapshots(snaps), sources

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="glint-gang-metrics",
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
