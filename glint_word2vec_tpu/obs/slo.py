"""Per-endpoint SLOs, multi-window burn rates, and the anomaly flight
recorder (ISSUE 18).

The serving data plane's overload machinery (ISSUE 7) and the fleet
balancer (ISSUE 12/13) emit counters, but nothing answers the operator
question "are we failing our users *right now*, and how fast": that is a
burn rate, not a counter. :class:`SloEngine` implements the standard
multi-window multi-burn-rate evaluation (Google SRE workbook ch. 5):
each endpoint owns an availability SLI (non-5xx fraction) and a latency
SLI (fraction of non-5xx responses under a threshold), bucketed into
10-second bins pruned at 6 hours, and evaluated over paired windows —
fast (5m AND 1h, trigger 14.4x) catches a sudden cliff within minutes
while the long window debounces blips; slow (30m AND 6h, trigger 6x)
catches a simmering leak. Snapshots carry the raw per-window good/bad
counts, so merging replicas is summing counts and recomputing burn —
the same mergeable-by-construction contract the serving aggregate uses.

:class:`FlightRecorder` is the anomaly postmortem half: when a breaker
opens, a shed burst fires (:class:`ShedBurstDetector`), an SLO enters
fast burn, or a rank dies, it snapshots every registered source (the
local span ring's last N seconds, the metrics snapshot, per-replica
scrapes at the balancer) into a ``flightrec-<seq>-<reason>/`` bundle —
rate-limited so a flapping trigger cannot fill the disk. Everything here
is stdlib-only and jax-free: it runs inside serving handlers and the
balancer, never on a device.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional

logger = logging.getLogger(__name__)

#: Evaluation windows (label -> seconds). The burn-rate pairs below
#: reference these labels; 6h is also the retention bound of the bucket
#: ring (longest window anything can ask about).
WINDOWS = {"5m": 300, "30m": 1800, "1h": 3600, "6h": 21600}

#: Multi-window alert pairs: (short window, long window, trigger burn).
#: Fast: 14.4x burn spends 2% of a 30-day budget in one hour. Slow: 6x
#: spends 10% in 6 hours. Both windows must exceed the trigger.
BURN_PAIRS = (
    ("fast", "5m", "1h", 14.4),
    ("slow", "30m", "6h", 6.0),
)

_BUCKET_SECONDS = 10
_RETENTION_SECONDS = WINDOWS["6h"] + _BUCKET_SECONDS


@dataclass
class SloObjective:
    """One endpoint's objectives. ``availability_target`` is the good
    fraction (non-5xx); ``latency_target`` the fraction of non-5xx
    responses that must finish under ``latency_threshold_ms``."""

    endpoint: str
    availability_target: float = 0.999
    latency_target: float = 0.99
    latency_threshold_ms: float = 250.0


class SloEngine:
    """Bucketed SLI counts + burn-rate evaluation for a set of
    objectives. Thread-safe; ``now_fn`` is injectable so tests can march
    a fake clock through the windows deterministically."""

    def __init__(self, objectives: Iterable[SloObjective],
                 now_fn: Callable[[], float] = time.monotonic):
        self._objectives = {o.endpoint: o for o in objectives}
        self._now = now_fn
        self._mu = threading.Lock()
        # endpoint -> {bucket_index -> [total, bad_avail, bad_latency]}
        self._buckets: Dict[str, Dict[int, List[int]]] = {
            e: {} for e in self._objectives
        }
        # Edge-triggered fast-burn state + throttle for the cheap
        # per-request check path.
        self._fast_burning: set = set()
        self._last_check = 0.0

    @classmethod
    def default_serving(cls, paths: Iterable[str],
                        now_fn: Callable[[], float] = time.monotonic
                        ) -> "SloEngine":
        """Objectives for the serving device paths, env-tunable:
        GLINT_SLO_AVAIL_TARGET / GLINT_SLO_LATENCY_TARGET /
        GLINT_SLO_LATENCY_MS."""
        avail = float(os.environ.get("GLINT_SLO_AVAIL_TARGET") or 0.999)
        lat_t = float(os.environ.get("GLINT_SLO_LATENCY_TARGET") or 0.99)
        lat_ms = float(os.environ.get("GLINT_SLO_LATENCY_MS") or 250.0)
        return cls(
            [SloObjective(p, avail, lat_t, lat_ms) for p in sorted(paths)],
            now_fn=now_fn,
        )

    def observe(self, endpoint: str, seconds: float, status: int) -> None:
        """Record one response. Endpoints without an objective are
        ignored (bounded cardinality by construction). 5xx counts
        against availability; the latency SLI is measured over non-5xx
        responses only (a fast 503 must not *improve* latency)."""
        obj = self._objectives.get(endpoint)
        if obj is None:
            return
        bad_avail = int(status) >= 500
        bad_lat = (
            not bad_avail
            and seconds * 1e3 > obj.latency_threshold_ms
        )
        idx = int(self._now() // _BUCKET_SECONDS)
        with self._mu:
            buckets = self._buckets[endpoint]
            b = buckets.get(idx)
            if b is None:
                b = buckets[idx] = [0, 0, 0]
                self._prune_locked(endpoint, idx)
            b[0] += 1
            b[1] += int(bad_avail)
            b[2] += int(bad_lat)

    def _prune_locked(self, endpoint: str, now_idx: int) -> None:
        floor = now_idx - _RETENTION_SECONDS // _BUCKET_SECONDS
        buckets = self._buckets[endpoint]
        for idx in [i for i in buckets if i < floor]:
            del buckets[idx]

    def _window_counts_locked(self, endpoint: str, now: float) -> dict:
        buckets = self._buckets[endpoint]
        now_idx = int(now // _BUCKET_SECONDS)
        out = {}
        for label, secs in WINDOWS.items():
            floor = now_idx - secs // _BUCKET_SECONDS
            total = bad_a = bad_l = 0
            for idx, (t, ba, bl) in buckets.items():
                if floor < idx <= now_idx:
                    total += t
                    bad_a += ba
                    bad_l += bl
            out[label] = {
                "total": total,
                "bad_availability": bad_a,
                "bad_latency": bad_l,
            }
        return out

    def snapshot(self) -> dict:
        """Mergeable SLO document: per-endpoint targets + raw per-window
        counts + derived burn rates and alert states. Merge replicas
        with :func:`merge_slo_snapshots` (sums counts, recomputes)."""
        now = self._now()
        with self._mu:
            per_ep = {}
            for ep, obj in self._objectives.items():
                per_ep[ep] = {
                    "availability_target": obj.availability_target,
                    "latency_target": obj.latency_target,
                    "latency_threshold_ms": obj.latency_threshold_ms,
                    "windows": self._window_counts_locked(ep, now),
                }
        return _derive_burns({"endpoints": per_ep})

    def fast_burn_transitions(self, min_interval: float = 5.0) -> list:
        """Endpoints that newly ENTERED fast burn since the last check
        (edge-triggered, throttled to one evaluation per
        ``min_interval`` seconds) — the flight-recorder trigger hook the
        serving handler calls on its response path."""
        now = self._now()
        with self._mu:
            if now - self._last_check < min_interval:
                return []
            self._last_check = now
        snap = self.snapshot()
        burning = {
            ep for ep, doc in snap["endpoints"].items()
            if doc["alerts"]["fast_burn"]
        }
        with self._mu:
            entered = sorted(burning - self._fast_burning)
            self._fast_burning = burning
        return entered


def _burn(bad: int, total: int, target: float) -> float:
    """Burn rate over one window: observed error rate / budgeted error
    rate. 0 with no traffic (absence of evidence is not an alert)."""
    if not total:
        return 0.0
    budget = 1.0 - target
    if budget <= 0:
        return 0.0
    return (bad / total) / budget


def _derive_burns(doc: dict) -> dict:
    """Fill in per-endpoint burn rates + alert booleans from raw window
    counts (shared by live snapshots and cross-replica merges, so a
    merged document derives identically)."""
    for ep_doc in doc["endpoints"].values():
        win = ep_doc["windows"]
        burns = {"availability": {}, "latency": {}}
        for label in WINDOWS:
            w = win[label]
            burns["availability"][label] = round(_burn(
                w["bad_availability"], w["total"],
                ep_doc["availability_target"],
            ), 3)
            burns["latency"][label] = round(_burn(
                w["bad_latency"], max(w["total"] - w["bad_availability"], 0),
                ep_doc["latency_target"],
            ), 3)
        def pair_fired(short: str, long_: str, trigger: float) -> bool:
            return any(
                burns[sli][short] > trigger and burns[sli][long_] > trigger
                for sli in ("availability", "latency")
            )

        # Literal keys on purpose (see BURN_PAIRS): graftlint's
        # prom-consistency rule statically maps every snapshot key the
        # renderers read back to a producer-side literal.
        alerts = {
            "fast_burn": pair_fired("5m", "1h", 14.4),
            "slow_burn": pair_fired("30m", "6h", 6.0),
        }
        ep_doc["burn_rates"] = burns
        ep_doc["alerts"] = alerts
    return doc


def merge_slo_snapshots(snaps: Iterable[dict]) -> Optional[dict]:
    """Fold per-replica SLO snapshots into one fleet document: window
    counts sum, targets come from the first replica carrying the
    endpoint (one config per fleet is the deployment contract), burns
    and alerts are re-derived from the summed counts."""
    snaps = [s for s in snaps if s and s.get("endpoints")]
    if not snaps:
        return None
    endpoints: Dict[str, dict] = {}
    for s in snaps:
        for ep, doc in s["endpoints"].items():
            agg = endpoints.get(ep)
            if agg is None:
                agg = endpoints[ep] = {
                    "availability_target": doc["availability_target"],
                    "latency_target": doc["latency_target"],
                    "latency_threshold_ms": doc["latency_threshold_ms"],
                    "windows": {
                        label: {
                            "total": 0,
                            "bad_availability": 0,
                            "bad_latency": 0,
                        } for label in WINDOWS
                    },
                }
            for label in WINDOWS:
                src = (doc.get("windows") or {}).get(label)
                if not src:
                    continue
                dst = agg["windows"][label]
                for k in dst:
                    dst[k] += int(src.get(k) or 0)
    return _derive_burns(
        {"endpoints": {e: endpoints[e] for e in sorted(endpoints)}}
    )


class ShedBurstDetector:
    """Edge detector for shed bursts: ``note()`` returns True when the
    shed count inside the sliding window first crosses the threshold
    (and re-arms only after the window drains below it), so the flight
    recorder sees one trigger per burst, not one per shed."""

    def __init__(self, threshold: int = 20, window_seconds: float = 10.0,
                 now_fn: Callable[[], float] = time.monotonic):
        self.threshold = max(1, int(threshold))
        self.window_seconds = float(window_seconds)
        self._now = now_fn
        self._mu = threading.Lock()
        self._times: List[float] = []
        self._armed = True

    def note(self) -> bool:
        now = self._now()
        with self._mu:
            self._times.append(now)
            floor = now - self.window_seconds
            self._times = [t for t in self._times if t >= floor]
            if len(self._times) >= self.threshold:
                if self._armed:
                    self._armed = False
                    return True
                return False
            self._armed = True
            return False


class FlightRecorder:
    """Postmortem bundle writer. ``sources`` are named zero-argument-ish
    callables (they receive the bundle's span window in seconds) whose
    JSON-serializable return values are written one file per source;
    :meth:`trigger` snapshots all of them into
    ``<out_dir>/flightrec-<seq>-<reason>/`` and finishes with
    ``meta.json`` (its presence marks the bundle complete). Triggers
    are rate-limited to one bundle per ``min_interval_seconds``; a
    failing source is recorded in the meta, never fatal — the recorder
    must not take down the data plane it is documenting."""

    def __init__(self, out_dir: str, *, window_seconds: float = 30.0,
                 min_interval_seconds: float = 60.0,
                 now_fn: Callable[[], float] = time.monotonic):
        self.out_dir = out_dir
        self.window_seconds = float(window_seconds)
        self.min_interval_seconds = float(min_interval_seconds)
        self._now = now_fn
        self._mu = threading.Lock()
        self._sources: Dict[str, Callable[[float], object]] = {}
        self._seq = 0
        self._last_trigger: Optional[float] = None
        self.triggered_total = 0
        self.suppressed_total = 0

    def add_source(self, name: str,
                   fn: Callable[[float], object]) -> None:
        with self._mu:
            self._sources[name] = fn

    def trigger(self, reason: str, **context) -> Optional[str]:
        """Write one bundle (or None when rate-limited). Never raises:
        this is called from breaker/handler paths that must survive a
        full disk."""
        now = self._now()
        with self._mu:
            if (self._last_trigger is not None
                    and now - self._last_trigger
                    < self.min_interval_seconds):
                self.suppressed_total += 1
                return None
            self._last_trigger = now
            self._seq += 1
            seq = self._seq
            sources = dict(self._sources)
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in reason
        )
        bundle = os.path.join(
            self.out_dir, f"flightrec-{seq:03d}-{safe_reason}"
        )
        try:
            from glint_word2vec_tpu.utils import atomic_write_json

            os.makedirs(bundle, exist_ok=True)
            meta = {
                "reason": reason,
                "context": context,
                "wall_time": time.time(),
                "window_seconds": self.window_seconds,
                "sequence": seq,
                "sources": {},
            }
            for name, fn in sorted(sources.items()):
                try:
                    doc = fn(self.window_seconds)
                    atomic_write_json(
                        os.path.join(bundle, f"{name}.json"), doc
                    )
                    meta["sources"][name] = "ok"
                except Exception as e:
                    meta["sources"][name] = f"error: {e}"
            # meta.json last: its presence marks the bundle complete
            # (a reader never consumes a half-written bundle).
            atomic_write_json(os.path.join(bundle, "meta.json"), meta)
        except Exception as e:
            logger.warning(
                "flight recorder bundle %s failed: %s", bundle, e
            )
            return None
        with self._mu:
            self.triggered_total += 1
        logger.warning(
            "flight recorder: wrote %s (reason=%s)", bundle, reason
        )
        return bundle

    def stats(self) -> dict:
        with self._mu:
            return {
                "out_dir": self.out_dir,
                "triggered_total": self.triggered_total,
                "suppressed_total": self.suppressed_total,
                "sources": sorted(self._sources),
            }
