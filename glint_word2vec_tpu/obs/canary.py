"""Divergence canary: rolling-loss NaN/Inf and explosion detection.

The reference's only divergence signal is the last positive dot product
printed in its periodic log line, left for a human to eyeball
(mllib:399-413). On a multi-hour TPU fit that is operationally useless:
a run that NaNs at hour two burns the remaining budget training garbage.
This is the TPU-native replacement — a rolling window over the per-step
SGNS loss with two trip conditions:

- **non-finite**: any NaN/Inf loss (the unambiguous failure);
- **explosion**: a loss more than ``factor`` times the window median
  once the window holds ``min_history`` healthy samples.

The canary itself only classifies; the caller (obs.ObsRun) decides
warn-vs-abort and owns the abort side effects (final checkpoint,
event-log flush, raising :class:`TrainingDiverged`).
"""

from __future__ import annotations

import math
from collections import deque
from typing import Optional


class TrainingDiverged(RuntimeError):
    """Raised by the abort-mode canary after the event log has been
    flushed; the fit loop writes the final ``ckpt-diverged`` table
    snapshot on the way out so the run is post-mortemable."""


class DivergenceCanary:
    """Rolling loss window; ``check`` returns None while healthy, else a
    one-line human-readable trip reason.

    A tripped (exploded or non-finite) sample is kept OUT of the window,
    so a sustained explosion keeps tripping instead of normalizing
    itself into the baseline median.
    """

    def __init__(self, window: int = 64, factor: float = 10.0,
                 min_history: int = 8):
        self.window: deque = deque(maxlen=max(2, int(window)))
        self.factor = float(factor)
        self.min_history = max(2, int(min_history))
        self.trips = 0
        self.last_reason: Optional[str] = None

    def _median(self) -> float:
        vals = sorted(self.window)
        mid = len(vals) // 2
        if len(vals) % 2:
            return vals[mid]
        return 0.5 * (vals[mid - 1] + vals[mid])

    def check(self, step: int, loss: float) -> Optional[str]:
        loss = float(loss)
        if not math.isfinite(loss):
            self.trips += 1
            self.last_reason = f"non-finite loss {loss} at step {step}"
            return self.last_reason
        if len(self.window) >= self.min_history:
            med = self._median()
            if med > 0 and loss > self.factor * med:
                self.trips += 1
                self.last_reason = (
                    f"loss {loss:.4g} at step {step} is {loss / med:.1f}x "
                    f"the rolling median {med:.4g} "
                    f"(threshold {self.factor:g}x)"
                )
                return self.last_reason
        self.window.append(loss)
        return None
