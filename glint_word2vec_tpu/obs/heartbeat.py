"""Live training heartbeat: /healthz + /metrics on the TRAINING process.

The reference offers no way to ask a running fit how it is doing — when
a multi-hour job stalls the only tools are grepping executor logs and
waiting for the final metrics dict. Here the training loop feeds a
:class:`TrainingStatus` snapshot (epoch/step progress, rolling
words/sec, host_frac, last loss, per-device memory stats, compile
counters, canary state) and an opt-in :class:`HeartbeatServer` serves it
read-only over HTTP:

  GET /healthz                    -> {"status": "ok"|"diverged", ...}
  GET /metrics                    -> the full JSON snapshot
  GET /metrics?format=prometheus  -> text exposition (scrapeable)

so a stuck run is diagnosable with curl instead of a debugger. Multihost
workers that can't bind ports mirror the same snapshot to an atomic JSON
status file instead (obs.ObsRun's ``status_file``).
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

logger = logging.getLogger(__name__)


def _finite_or_none(v):
    """Non-finite floats serialize as bare NaN/Infinity — invalid JSON
    that breaks strict consumers (JSON.parse, jq) exactly on the
    diverging run the heartbeat exists to diagnose. None round-trips as
    null; the Prometheus renderer maps it back to NaN, which IS valid
    there."""
    if v is None:
        return None
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def device_memory_stats() -> dict:
    """Per-device memory stats where the backend reports them
    (``jax.local_devices()[i].memory_stats()``: TPU runtimes do, CPU
    usually returns None). Never raises — the heartbeat must stay up
    while the backend is initializing or on backends without the query."""
    out: dict = {}
    try:
        import jax

        for i, d in enumerate(jax.local_devices()):
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if not ms:
                continue
            stats = {
                k: int(v)
                for k, v in ms.items()
                if isinstance(v, (int, float))
                and ("bytes" in k or "size" in k or "limit" in k)
            }
            if stats:
                out[str(i)] = stats
    except Exception:
        pass
    return out


class TrainingStatus:
    """Thread-safe snapshot of a running fit: written by the training
    loop (cheap, per dispatch group), read by the heartbeat server and
    the status-file writer."""

    #: (wall time, words_done) samples kept for the rolling words/sec.
    ROLLING = 32

    def __init__(self, *, pipeline: str = "", total_epochs: int = 0,
                 total_words: int = 0, metrics=None, engine=None,
                 recorder=None, ledger=None, slo=None):
        self._mu = threading.Lock()
        #: Optional utils.metrics.StepTimeLedger — the step-time
        #: attribution breakdown surfaced under ``steptime`` in every
        #: snapshot (and merged across ranks by obs.aggregate).
        self._ledger = ledger
        #: Optional obs.slo.SloEngine — burn-rate objectives surfaced
        #: under ``slo`` (ISSUE 18); None keeps snapshots unchanged.
        self._slo = slo
        self.pipeline = pipeline
        self.total_epochs = int(total_epochs)
        self.total_words = int(total_words)
        self._metrics = metrics
        self._engine = engine
        self._recorder = recorder
        self.started = time.time()
        # starting|running|done|diverged|failed|unhealthy
        self.state = "starting"
        self.epoch = 0
        self.step = 0
        self.words_done = 0
        self.alpha: Optional[float] = None
        self.canary = {"mode": "off", "trips": 0, "last_reason": None}
        self.unhealthy_reason: Optional[str] = None
        # Streaming gauges (ISSUE 10): None until a fit_stream loop
        # calls set_streaming, so batch fits serve unchanged snapshots.
        self._streaming: Optional[dict] = None
        # Bulk-transform gauges (ISSUE 17): None until a transform_file
        # pipeline calls set_transform — training runs serve unchanged
        # snapshots.
        self._transform: Optional[dict] = None
        self._last_publish_unix: Optional[float] = None
        # Supervisor handshake (parallel/supervisor.py): echo the launch
        # generation back in every snapshot so the supervisor can tell a
        # live heartbeat of the CURRENT gang from a stale pre-restart
        # status file with the same path.
        gen = os.environ.get("GLINT_SUPERVISOR_GEN")
        try:
            self.supervisor_generation = (
                int(gen) if gen is not None else None
            )
        except ValueError:
            self.supervisor_generation = None
        self._rolling: deque = deque(maxlen=self.ROLLING)

    def attach(self, *, metrics=None, engine=None, recorder=None,
               ledger=None, slo=None) -> None:
        with self._mu:
            if metrics is not None:
                self._metrics = metrics
            if engine is not None:
                self._engine = engine
            if recorder is not None:
                self._recorder = recorder
            if ledger is not None:
                self._ledger = ledger
            if slo is not None:
                self._slo = slo

    def update(self, *, epoch=None, step=None, words_done=None, alpha=None,
               state=None) -> None:
        with self._mu:
            if epoch is not None:
                self.epoch = int(epoch)
            if step is not None:
                self.step = int(step)
            if words_done is not None:
                self.words_done = int(words_done)
                self._rolling.append((time.time(), self.words_done))
            if alpha is not None:
                self.alpha = float(alpha)
            if state is not None:
                self.state = state

    def set_canary(self, mode: str, trips: int, last_reason) -> None:
        with self._mu:
            self.canary = {
                "mode": mode, "trips": int(trips), "last_reason": last_reason,
            }

    def set_streaming(self, *, words_streamed=0, sentences_streamed=0,
                      oov_words=0, vocab_size=0, promoted_words=0,
                      extra_rows_free=0, sketch_fill=0.0,
                      noise_drift_l1=None, stream_lag_seconds=None,
                      generations_published=0, last_publish_unix=None,
                      buffer_fill=None) -> None:
        """Install the streaming trainer's gauge set (ISSUE 10): stream
        progress, online-vocab growth, distribution drift, and publish
        cadence — the keys ``training_to_prometheus`` renders as
        ``glint_stream_*``. Publish age is computed at snapshot time
        from the stored unix stamp so the gauge stays live between
        updates."""
        with self._mu:
            self._last_publish_unix = last_publish_unix
            # Counters arrive as plain host ints from the trainer; the
            # float-ish gauges go through the NaN-safe JSON guard.
            self._streaming = {
                "words_streamed_total": words_streamed,
                "sentences_streamed_total": sentences_streamed,
                "oov_words_total": oov_words,
                "stream_vocab_size": vocab_size,
                "promoted_words_total": promoted_words,
                "extra_rows_free": extra_rows_free,
                "sketch_fill": _finite_or_none(sketch_fill),
                "noise_drift_l1": _finite_or_none(noise_drift_l1),
                "stream_lag_seconds": _finite_or_none(stream_lag_seconds),
                "generations_published_total": generations_published,
                "buffer_fill": _finite_or_none(buffer_fill),
            }

    def set_transform(self, *, sentences_done=0, input_sentences=0,
                      sentences_per_sec=0.0, shards_committed=0,
                      shards_skipped=0, bucket_fill=None,
                      producer_wait_seconds=0.0, dispatch_seconds=0.0,
                      post_warmup_compiles=0) -> None:
        """Install the bulk-transform gauge set (ISSUE 17): stream
        progress, shard commits/skips, packing density, and the
        host-stall + compile-freedom health signals — the keys
        ``training_to_prometheus`` renders as ``glint_transform_*`` and
        the gang aggregate rolls up across ranks."""
        with self._mu:
            # Counters arrive as plain host ints from the pipeline; the
            # float-ish gauges go through the NaN-safe JSON guard.
            self._transform = {
                "sentences_done_total": sentences_done,
                "input_sentences": input_sentences,
                "sentences_per_sec": _finite_or_none(sentences_per_sec),
                "shards_committed_total": shards_committed,
                "shards_skipped_total": shards_skipped,
                "bucket_fill": _finite_or_none(bucket_fill),
                "producer_wait_seconds": _finite_or_none(
                    producer_wait_seconds
                ),
                "dispatch_seconds": _finite_or_none(dispatch_seconds),
                "post_warmup_compiles_total": post_warmup_compiles,
            }

    def mark_unhealthy(self, reason: str) -> None:
        """Flip the worker to ``unhealthy`` so ``/healthz`` answers 503
        (fleet probes and the supervisor work off status codes, not
        body parsing). Terminal states already reported (done/diverged/
        failed) are not downgraded."""
        with self._mu:
            if self.state not in ("done", "diverged", "failed"):
                self.state = "unhealthy"
            self.unhealthy_reason = str(reason)

    def _rolling_wps(self) -> float:
        if len(self._rolling) < 2:
            return 0.0
        (t0, w0), (t1, w1) = self._rolling[0], self._rolling[-1]
        return (w1 - w0) / max(t1 - t0, 1e-9)

    def snapshot(self, include_devices: bool = True) -> dict:
        with self._mu:
            m, eng, rec = self._metrics, self._engine, self._recorder
            ledger, slo = self._ledger, self._slo
            snap = {
                "state": self.state,
                "pipeline": self.pipeline,
                "uptime_seconds": round(time.time() - self.started, 2),
                "epoch": self.epoch,
                "total_epochs": self.total_epochs,
                "step": self.step,
                "words_done": self.words_done,
                "total_words": self.total_words,
                "words_per_sec_rolling": round(self._rolling_wps(), 1),
                "alpha": _finite_or_none(self.alpha),
                "canary": dict(self.canary),
                "supervisor_generation": self.supervisor_generation,
                "unhealthy_reason": self.unhealthy_reason,
            }
            if self._streaming is not None:
                streaming = dict(self._streaming)
                streaming["last_publish_age_seconds"] = _finite_or_none(
                    time.time() - self._last_publish_unix
                    if self._last_publish_unix else None
                )
                snap["streaming"] = streaming
            if self._transform is not None:
                snap["transform"] = dict(self._transform)
        if m is not None:
            # last_loss is whatever the metrics layer last SYNCED — the
            # heartbeat never forces a device sync of its own.
            ht, st = m.host_time, m.step_time
            snap.update({
                "last_loss": _finite_or_none(m.last_loss),
                "host_time": round(ht, 2),
                "step_time": round(st, 2),
                "host_frac": round(ht / max(ht + st, 1e-9), 3),
                # Rolling host-side dispatch-starvation proxy (ISSUE 5):
                # blocking checkpoint saves + batch-producer waits +
                # epoch-boundary compaction syncs. Async checkpointing,
                # deferred readbacks, and prefetch overlap each shrink it.
                "device_stall_seconds": round(
                    getattr(m, "stall_time", 0.0), 3
                ),
            })
        if eng is not None:
            snap["table_version"] = int(getattr(eng, "table_version", 0))
            snap["query_compiles"] = int(getattr(eng, "query_compiles", 0))
            ck_stats = getattr(eng, "checkpoint_stats", None)
            if ck_stats is not None:
                try:
                    ck = ck_stats()
                except Exception:  # telemetry must never kill the server
                    ck = {}
                snap["pending_async_saves"] = ck.get(
                    "pending_async_saves", 0
                )
                snap["async_save_waits"] = ck.get("async_save_waits", 0)
                snap["checkpoint_write_seconds"] = _finite_or_none(
                    ck.get("checkpoint_write_seconds")
                )
                snap["last_checkpoint_age_seconds"] = _finite_or_none(
                    ck.get("last_checkpoint_age_seconds")
                )
                # Shard-streaming checkpoint telemetry (ISSUE 15).
                snap["checkpoint_shard_write_seconds"] = _finite_or_none(
                    ck.get("checkpoint_shard_write_seconds")
                )
                snap["checkpoint_shard_verify_seconds"] = _finite_or_none(
                    ck.get("checkpoint_shard_verify_seconds")
                )
                snap["checkpoint_shards_skipped"] = ck.get(
                    "checkpoint_shards_skipped", 0
                )
            ex_stats = getattr(eng, "exchange_stats", None)
            if ex_stats is not None:
                # Touched-row replica-exchange counters (ISSUE 15):
                # zeros on non-exchange fits, summed into the gang
                # rollup by obs.aggregate.
                try:
                    exs = ex_stats()
                except Exception:
                    exs = {}
                snap["exchange_bytes_total"] = exs.get(
                    "exchange_bytes_total", 0
                )
                snap["exchange_rows_total"] = exs.get(
                    "exchange_rows_total", 0
                )
                snap["exchange_overflow_total"] = exs.get(
                    "exchange_overflow_total", 0
                )
                snap["exchange_syncs_total"] = exs.get(
                    "exchange_syncs_total", 0
                )
                # ISSUE 16 wire-layer counters: bytes by wire format,
                # coalesced dispatch groups, flush rounds, world=1
                # skips, the two-level per-hop byte split, the live
                # capacity gauge (+ adaptation counters), and the
                # error-feedback residual gauge.
                for k in (
                    "exchange_bytes_wire_fp32_total",
                    "exchange_bytes_wire_bf16_total",
                    "exchange_bytes_wire_int8_total",
                    "exchange_groups_total",
                    "exchange_flushes_total",
                    "exchange_world1_skips_total",
                    "exchange_intra_bytes_total",
                    "exchange_inter_bytes_total",
                    "exchange_capacity_grows_total",
                    "exchange_capacity_shrinks_total",
                ):
                    snap[k] = exs.get(k, 0)
                snap["exchange_capacity"] = exs.get("exchange_capacity")
                snap["exchange_residual_abs"] = exs.get(
                    "exchange_residual_abs", 0.0
                )
        if rec is not None:
            snap["events"] = rec.counts()
        if ledger is not None:
            # Step-time attribution (ISSUE 8): per-phase wall seconds
            # with the histogram state the gang aggregator merges.
            snap["steptime"] = ledger.snapshot()
        if slo is not None:
            # SLO burn rates (ISSUE 18): training-side objectives (e.g.
            # a step-latency SLI) rendered as glint_training_slo_*.
            snap["slo"] = slo.snapshot()
        if include_devices:
            snap["device_memory"] = device_memory_stats()
        return snap


class HeartbeatServer:
    """Background stdlib HTTP server over one :class:`TrainingStatus`.

    Read-only (GET only), daemon-threaded, ephemeral-port capable
    (``port=0``; the bound port is on ``self.port``) — safe to park next
    to a multi-hour fit."""

    def __init__(self, status: TrainingStatus, host: str = "127.0.0.1",
                 port: int = 0):
        from glint_word2vec_tpu.obs.prometheus import training_to_prometheus

        server = self
        self.status = status

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("heartbeat: " + fmt, *args)

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urlparse(self.path)
                if url.path == "/healthz":
                    snap = server.status.snapshot(include_devices=False)
                    ok = snap["state"] not in (
                        "diverged", "failed", "unhealthy"
                    )
                    body = json.dumps({
                        "status": "ok" if ok else snap["state"],
                        "state": snap["state"],
                        "pipeline": snap["pipeline"],
                        "epoch": snap["epoch"],
                        "total_epochs": snap["total_epochs"],
                        "step": snap["step"],
                        "words_done": snap["words_done"],
                        "words_per_sec_rolling":
                            snap["words_per_sec_rolling"],
                        "unhealthy_reason": snap["unhealthy_reason"],
                    }).encode()
                    # 503, not 500: the probe contract for "this worker
                    # is unhealthy, act on it" — fleet probes and the
                    # supervisor branch on the status code alone.
                    self._send(200 if ok else 503, body, "application/json")
                elif url.path == "/metrics":
                    snap = server.status.snapshot()
                    fmt = parse_qs(url.query).get("format", ["json"])[0]
                    if fmt == "prometheus":
                        self._send(
                            200, training_to_prometheus(snap).encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    else:
                        self._send(200, json.dumps(snap).encode(),
                                   "application/json")
                else:
                    self._send(
                        404,
                        json.dumps({"error": f"no route {url.path}"}).encode(),
                        "application/json",
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="glint-heartbeat",
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
