"""Run-wide observability: the layer both training and serving feed.

Four pieces (ISSUE 3), all opt-in and all zero-cost when disabled:

- **Span event log** (:mod:`obs.events`): a thread-safe bounded ring +
  JSONL sink instrumenting the fit loops' phases (subsample-compact,
  host batching, device step dispatch, checkpoint save/restore) and
  engine-level events (table mutations ticking ``table_version``,
  query-shape compiles, warmup), exportable as a Chrome-trace JSON for
  side-by-side reading with device xplane traces
  (``scripts/trace_summarize.py --host-spans``).
- **Live training heartbeat** (:mod:`obs.heartbeat`): ``/healthz`` +
  ``/metrics`` (JSON and Prometheus) on the training process, plus an
  atomic status-file mirror for multihost workers that can't bind ports.
- **Divergence canary** (:mod:`obs.canary`): rolling-loss NaN/explosion
  detection, warn-or-abort; abort writes a final checkpoint and flushes
  the event log before raising :class:`TrainingDiverged`.
- **Prometheus exposition** (:mod:`obs.prometheus`): text-format
  renderers for the training and serving snapshots, shared by the
  heartbeat server and ``serving.ModelServer``.

The training loops own one :class:`ObsRun` per fit (``start_run``
returns the shared no-op :data:`NULL_RUN` when observability is off, so
the hot loop calls its hooks unconditionally).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Optional

from glint_word2vec_tpu.obs import events
from glint_word2vec_tpu.obs.canary import DivergenceCanary, TrainingDiverged
from glint_word2vec_tpu.obs.events import EventRecorder
from glint_word2vec_tpu.obs.heartbeat import HeartbeatServer, TrainingStatus
from glint_word2vec_tpu.utils.metrics import StepTimeLedger

__all__ = [
    "DivergenceCanary", "EventRecorder", "HeartbeatServer", "NULL_RUN",
    "ObsConfig", "ObsRun", "StepTimeLedger", "TrainingDiverged",
    "TrainingStatus", "start_run",
]

logger = logging.getLogger(__name__)


@dataclass
class ObsConfig:
    """Observability configuration for ONE fit invocation.

    Deliberately NOT part of ``Word2VecParams``: it never affects the
    trained model and must not be persisted into ``params.json`` (a
    model is loadable on a machine with none of these paths/ports)."""

    #: JSONL sink receiving every span/event (None = ring only).
    event_log: Optional[str] = None
    #: Bounded in-memory event ring size; overflow counts as dropped.
    event_capacity: int = 65536
    #: Chrome-trace (chrome://tracing / Perfetto) JSON written at run end.
    chrome_trace: Optional[str] = None
    #: Force the event recorder on even with no sink configured.
    record_events: bool = False
    #: Heartbeat HTTP port (None = no server; 0 = ephemeral — the bound
    #: port is published back on ``bound_port``).
    status_port: Optional[int] = None
    status_host: str = "127.0.0.1"
    #: Atomic JSON mirror of the status snapshot, for multihost workers
    #: that can't bind ports; rewritten at most every status_interval s.
    status_file: Optional[str] = None
    status_interval: float = 1.0
    #: Divergence canary: "off", "warn" (log + event), or "abort"
    #: (final checkpoint + event flush, then TrainingDiverged).
    canary: str = "off"
    canary_window: int = 64
    canary_factor: float = 10.0
    #: Steps between canary loss syncs. Each check forces one device
    #: sync (blocking the async dispatch pipeline), so keep >> 1 on
    #: real runs; 1 checks every group.
    canary_check_every: int = 32
    #: Per-run step-time attribution artifact (STEPTIME.json): the
    #: ledger's phase breakdown + per-phase quantiles, written
    #: atomically at run end. The ledger itself runs whenever
    #: observability is enabled (it rides the fit loops' ObsRun.span
    #: hooks); this only controls the file dump.
    steptime_path: Optional[str] = None
    #: Filled in by start_run when a heartbeat server binds.
    bound_port: Optional[int] = None

    @property
    def wants_recorder(self) -> bool:
        return bool(self.event_log or self.chrome_trace or self.record_events)

    @property
    def enabled(self) -> bool:
        return bool(
            self.wants_recorder
            or self.status_port is not None
            or self.status_file
            or self.canary != "off"
            or self.steptime_path
        )


#: Span-name -> ledger-phase map for the step-time attribution ledger
#: (ISSUE 8). Only these fit-thread spans are accounted — nested or
#: engine-internal spans (subword_expand inside device_steps, ckpt_write
#: on the writer thread) are deliberately absent so phase totals stay a
#: non-overlapping decomposition of the fit thread's wall clock.
_LEDGER_PHASE_OF = {
    "device_steps": "dispatch",
    "readback_harvest": "readback_harvest",
    "host_batch": "producer_wait",
    "subsample_compact": "compact",
    "subsample_prefetch": "compact",
    "ckpt_snapshot": "checkpoint",
    "checkpoint_save": "checkpoint",
    "checkpoint_restore": "checkpoint",
    "upload_corpus": "other",
}


class _LedgerSpan:
    """Context manager charging a span's wall time to a ledger phase on
    top of (optionally) recording it. ``with`` yields the inner span so
    ``span.update(...)`` keeps working at instrumentation sites."""

    __slots__ = ("_ledger", "_phase", "_inner", "_t0")

    def __init__(self, ledger, phase: str, inner):
        self._ledger = ledger
        self._phase = phase
        self._inner = inner

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self._inner.__enter__()

    def __exit__(self, *exc):
        self._ledger.account(
            self._phase, time.perf_counter() - self._t0
        )
        return self._inner.__exit__(*exc)


class _NullRun:
    """Disabled observability: every hook the fit loops call is a no-op,
    so instrumentation costs ~nothing when off."""

    recorder = None
    canary = None
    status = None
    server = None
    ledger = None

    def span(self, name: str, **args):
        return events.NULL_SPAN

    def event(self, name: str, **args) -> None:
        pass

    def steptime_totals(self):
        return None

    def attach_metrics(self, metrics) -> None:
        pass

    def update(self, **kw) -> None:
        pass

    def update_streaming(self, **kw) -> None:
        pass

    def update_transform(self, **kw) -> None:
        pass

    def observe_losses(self, first_step: int, losses, n_real: int) -> None:
        pass

    def close(self, failed: bool = False) -> None:
        pass


NULL_RUN = _NullRun()


class ObsRun:
    """Wired-up observability for one fit: event recorder (installed as
    the process-wide recorder so engine-level sites emit too), heartbeat
    server, status-file mirror, and divergence canary — owned by the
    training loop through the four hooks ``span``/``update``/
    ``observe_losses``/``close``."""

    def __init__(self, config: ObsConfig, *, pipeline: str = "",
                 total_epochs: int = 0, total_words: int = 0, engine=None):
        self.config = config
        self.recorder = (
            EventRecorder(config.event_capacity, config.event_log)
            if config.wants_recorder else None
        )
        #: Step-time attribution ledger (ISSUE 8): every phase-mapped
        #: ObsRun.span charges it, so the breakdown exists whenever
        #: observability is on — heartbeat, Prometheus, STEPTIME.json.
        self.ledger = StepTimeLedger()
        self._prev_recorder = events.get_recorder()
        events.set_recorder(self.recorder)
        try:
            self.canary = (
                DivergenceCanary(window=config.canary_window,
                                 factor=config.canary_factor)
                if config.canary != "off" else None
            )
            self.status = TrainingStatus(
                pipeline=pipeline, total_epochs=total_epochs,
                total_words=total_words, engine=engine,
                recorder=self.recorder, ledger=self.ledger,
            )
            if self.canary is not None:
                self.status.set_canary(config.canary, 0, None)
            self.server: Optional[HeartbeatServer] = None
            if config.status_port is not None:
                self.server = HeartbeatServer(
                    self.status, config.status_host, config.status_port
                )
                self.server.start()
                config.bound_port = self.server.port
                logger.info(
                    "training heartbeat on http://%s:%d "
                    "(/healthz, /metrics)",
                    self.server.host, self.server.port,
                )
        except BaseException:
            # A constructor failure (e.g. EADDRINUSE on --status-port)
            # yields no ObsRun for the fit loop to close(): uninstall the
            # process-wide recorder and release the sink here or they
            # leak for the process lifetime.
            events.set_recorder(self._prev_recorder)
            if self.recorder is not None:
                self.recorder.close()
            raise
        self._status_written = 0.0
        self._since_check = 0
        self._aborted = False
        self._closed = False
        self.status.update(state="running")
        self.event("run_start", pipeline=pipeline, total_epochs=total_epochs)
        self._write_status(force=True)

    def attach_metrics(self, metrics) -> None:
        self.status.attach(metrics=metrics)

    # -- hooks for the fit loops ---------------------------------------

    def span(self, name: str, **args):
        inner = (
            self.recorder.span(name, **args)
            if self.recorder is not None else events.NULL_SPAN
        )
        phase = _LEDGER_PHASE_OF.get(name)
        if phase is None:
            return inner
        return _LedgerSpan(self.ledger, phase, inner)

    def steptime_totals(self) -> dict:
        """{phase: seconds} (unattributed gap folded into ``other``) —
        what the fit loops surface in ``training_metrics``."""
        return {
            p: round(s, 3) for p, s in self.ledger.totals().items()
        }

    def event(self, name: str, **args) -> None:
        if self.recorder is not None:
            self.recorder.event(name, **args)

    def update(self, **kw) -> None:
        self.status.update(**kw)
        self._write_status()

    def update_streaming(self, **kw) -> None:
        """Streaming-trainer gauge hook (ISSUE 10): forwards to
        ``TrainingStatus.set_streaming`` and mirrors the status file on
        the usual cadence."""
        self.status.set_streaming(**kw)
        self._write_status()

    def update_transform(self, **kw) -> None:
        """Bulk-transform gauge hook (ISSUE 17): forwards to
        ``TrainingStatus.set_transform`` and mirrors the status file on
        the usual cadence — the supervisor's liveness sweep reads the
        same heartbeat it reads for training ranks."""
        self.status.set_transform(**kw)
        self._write_status()

    def observe_losses(self, first_step: int, losses, n_real: int) -> None:
        """Canary hook, called after each dispatched group with the (K,)
        lazy per-step loss array. Syncs ONE loss every
        ``canary_check_every`` steps (a sync blocks the dispatch
        pipeline — that cost is the whole reason for the cadence) and
        runs it through the rolling window. Warn mode logs and records
        an event; abort mode flushes the event log and raises
        :class:`TrainingDiverged` — the fit loop writes the final
        checkpoint on the way out."""
        if self.canary is None or n_real <= 0:
            return
        self._since_check += n_real
        if self._since_check < max(1, self.config.canary_check_every):
            return
        self._since_check = 0
        step = first_step + n_real
        try:
            val = float(losses[n_real - 1])
        except Exception as e:
            # Under async dispatch a poisoned buffer raises at read
            # time: the failed sync IS the divergence signal.
            logger.warning("canary loss sync failed at step %d: %s", step, e)
            val = float("nan")
        reason = self.canary.check(step, val)
        if reason is None:
            return
        self.status.set_canary(
            self.config.canary, self.canary.trips, reason
        )
        self.event("canary_trip", step=step, mode=self.config.canary,
                   reason=reason)
        if self.config.canary == "abort":
            self._aborted = True
            self.status.update(state="diverged")
            if self.recorder is not None:
                self.recorder.flush()
            self._write_status(force=True)
            raise TrainingDiverged(reason)
        logger.warning("divergence canary: %s", reason)

    # -- status file ----------------------------------------------------

    def _write_status(self, force: bool = False) -> None:
        path = self.config.status_file
        if not path:
            return
        now = time.time()
        if not force and now - self._status_written < self.config.status_interval:
            return
        self._status_written = now
        from glint_word2vec_tpu.utils import atomic_write_json

        try:
            atomic_write_json(path, self.status.snapshot())
        except OSError as e:
            logger.warning("status-file write to %s failed: %s", path, e)
        # Ride the (throttled) status cadence to keep the JSONL event
        # sink near-current on disk: a SIGKILLed worker's last ~1s of
        # events is then recoverable by the supervisor's crash flight
        # recorder (the postmortem bundle copies the sink file).
        if self.recorder is not None:
            self.recorder.flush()

    def close(self, failed: bool = False) -> None:
        """Idempotent teardown: final state, Chrome-trace export, JSONL
        flush/close, recorder uninstall, final status write, server stop.

        The fit loops call ``close(failed=True)`` from their generic
        exception handler and plain ``close()`` from ``finally`` (first
        call wins): a crashed run must never publish a status file that
        looks like success, and a successful run must not be misread as
        failed just because a caller invoked fit inside an ``except``
        block (which is why this takes an explicit flag instead of
        sniffing ``sys.exc_info``)."""
        if self._closed:
            return
        self._closed = True
        if self._aborted:
            state = "diverged"
        elif failed:
            state = "failed"
        else:
            state = "done"
        self.status.update(state=state)
        self.event("run_end", state=state)
        self.ledger.finalize()
        if self.config.steptime_path:
            try:
                self.ledger.dump(self.config.steptime_path)
            except OSError as e:
                logger.warning(
                    "STEPTIME dump to %s failed: %s",
                    self.config.steptime_path, e,
                )
        if self.recorder is not None:
            if self.config.chrome_trace:
                self.recorder.export_chrome_trace(self.config.chrome_trace)
            self.recorder.close()
        events.set_recorder(self._prev_recorder)
        self._write_status(force=True)
        if self.server is not None:
            self.server.stop()
            self.server = None


def start_run(config: Optional[ObsConfig], **kw):
    """:data:`NULL_RUN` when observability is off; a live ObsRun else."""
    if config is None or not config.enabled:
        return NULL_RUN
    return ObsRun(config, **kw)
