"""Prometheus text exposition (format version 0.0.4) for the snapshots
``utils/metrics.py`` and ``obs/heartbeat.py`` already compute.

Pure functions dict -> text so both HTTP layers (the serving
``ModelServer`` and the training heartbeat) render
``/metrics?format=prometheus`` from the exact same snapshot their JSON
endpoint serves — no second bookkeeping path to drift. Also exports
:func:`lint_prometheus_text`, the text-format validator the tests and
the CI obs smoke run over every rendered exposition.
"""

from __future__ import annotations

import math
import re


def _esc(v) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _num(v) -> str:
    if v is None:
        return "NaN"
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class _Prom:
    """Tiny exposition writer: HELP/TYPE heads + sample lines."""

    def __init__(self):
        self.lines = []

    def head(self, name: str, mtype: str, help_: str) -> None:
        self.lines.append(f"# HELP {name} {help_}")
        self.lines.append(f"# TYPE {name} {mtype}")

    def sample(self, name: str, labels, value, exemplar=None) -> None:
        if labels:
            lab = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
            line = f"{name}{{{lab}}} {_num(value)}"
        else:
            line = f"{name} {_num(value)}"
        if exemplar and exemplar.get("trace_id"):
            # OpenMetrics-style exemplar suffix: a trace id pinned to
            # one recent observation, so a dashboard quantile links
            # straight to the trace that produced it.
            line += (
                f' # {{trace_id="{_esc(exemplar["trace_id"])}"}}'
                f' {_num(exemplar.get("value"))}'
            )
        self.lines.append(line)

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


# ----------------------------------------------------------------------
# Training heartbeat exposition (obs/heartbeat.TrainingStatus.snapshot)
# ----------------------------------------------------------------------


def training_to_prometheus(snap: dict) -> str:
    """Render a TrainingStatus snapshot as scrape-ready text."""
    p = _Prom()
    p.head("glint_training_info", "gauge",
           "Run metadata carried as labels; value is always 1.")
    p.sample("glint_training_info",
             {"pipeline": snap.get("pipeline", ""),
              "state": snap.get("state", "")}, 1)
    gauges = [
        ("glint_training_epoch", "epoch", "Current epoch (0-based)."),
        ("glint_training_total_epochs", "total_epochs",
         "Configured epoch count."),
        ("glint_training_words_per_sec", "words_per_sec_rolling",
         "Rolling trained-words/sec over the recent update window."),
        ("glint_training_alpha", "alpha", "Current annealed learning rate."),
        ("glint_training_last_loss", "last_loss",
         "Most recently synced per-step loss (NaN until first sync)."),
        ("glint_training_host_frac", "host_frac",
         "Fraction of accounted wall time spent in host batching."),
        ("glint_training_device_stall_seconds", "device_stall_seconds",
         "Host-side dispatch-starvation proxy: blocking checkpoint "
         "saves + batch-producer waits + compaction syncs."),
        ("glint_training_pending_async_saves", "pending_async_saves",
         "Async checkpoint snapshots currently in flight (0 or 1)."),
        ("glint_training_checkpoint_write_seconds",
         "checkpoint_write_seconds",
         "Wall seconds of the most recent checkpoint write job."),
        ("glint_training_last_checkpoint_age_seconds",
         "last_checkpoint_age_seconds",
         "Seconds since the last committed checkpoint (NaN before any)."),
        ("glint_training_checkpoint_shard_write_seconds",
         "checkpoint_shard_write_seconds",
         "Seconds writing+hashing table shard blocks in the most "
         "recent checkpoint save (shard-streaming path, NaN before "
         "any)."),
        ("glint_training_checkpoint_shard_verify_seconds",
         "checkpoint_shard_verify_seconds",
         "Seconds verifying per-shard manifests in the most recent "
         "checkpoint stage/restore (NaN before any)."),
        ("glint_training_exchange_capacity", "exchange_capacity",
         "Live touched-row exchange buffer capacity (adapts from the "
         "observed high-water mark unless pinned; NaN before any "
         "exchange round)."),
        ("glint_training_exchange_residual_abs", "exchange_residual_abs",
         "Max-abs of the int8 error-feedback residual carry after the "
         "latest encode (0 on exact wires and right after a flush)."),
        ("glint_training_uptime_seconds", "uptime_seconds",
         "Seconds since the fit's observability run started."),
        ("glint_training_table_version", "table_version",
         "Engine table-mutation counter (serving caches validate on it)."),
        ("glint_training_supervisor_generation", "supervisor_generation",
         "Supervisor launch generation echoed by the worker (NaN when "
         "the fit is unsupervised)."),
        ("glint_training_diverged", None,
         "1 when the divergence canary aborted the run, else 0."),
    ]
    for name, key, help_ in gauges:
        p.head(name, "gauge", help_)
        if key is None:
            p.sample(name, None, 1 if snap.get("state") == "diverged" else 0)
        else:
            p.sample(name, None, snap.get(key))
    counters = [
        ("glint_training_steps_total", "step", "Optimizer steps completed."),
        ("glint_training_words_done_total", "words_done",
         "Trained words (pre-subsampling accounting)."),
        ("glint_training_query_compiles_total", "query_compiles",
         "Query-op shapes jit-compiled by the engine."),
        ("glint_training_async_save_waits_total", "async_save_waits",
         "Checkpoint requests that blocked on a still-in-flight "
         "snapshot (checkpoint back-pressure)."),
        ("glint_training_exchange_bytes_total", "exchange_bytes_total",
         "Replica-exchange bytes this rank shipped (headers + padded "
         "id/delta buffers, or full deltas on dense/spill rounds)."),
        ("glint_training_exchange_rows_total", "exchange_rows_total",
         "Touched table rows this rank harvested into exchange "
         "payloads (pre-padding, both tables)."),
        ("glint_training_exchange_overflow_total",
         "exchange_overflow_total",
         "Exchange rounds whose touched rows overflowed the capacity "
         "buffer and spilled to the dense path."),
        ("glint_training_exchange_syncs_total", "exchange_syncs_total",
         "Replica-exchange reconciliation rounds completed."),
        ("glint_training_exchange_bytes_wire_fp32_total",
         "exchange_bytes_wire_fp32_total",
         "Exchange bytes shipped on fp32-encoded rounds (exact sparse "
         "wire, plus every dense/spill/flush round)."),
        ("glint_training_exchange_bytes_wire_bf16_total",
         "exchange_bytes_wire_bf16_total",
         "Exchange bytes shipped on bf16-encoded sparse rounds."),
        ("glint_training_exchange_bytes_wire_int8_total",
         "exchange_bytes_wire_int8_total",
         "Exchange bytes shipped on int8-encoded sparse rounds "
         "(per-row maxabs scales + error feedback)."),
        ("glint_training_exchange_groups_total",
         "exchange_groups_total",
         "Dispatch groups folded into exchange rounds (> syncs when "
         "round coalescing accumulates several groups per round)."),
        ("glint_training_exchange_flushes_total",
         "exchange_flushes_total",
         "Checkpoint flush rounds (error-feedback carry drained "
         "through an exact fp32 wire round)."),
        ("glint_training_exchange_world1_skips_total",
         "exchange_world1_skips_total",
         "Exchange rounds short-circuited at world=1 (no wire, zero "
         "bytes)."),
        ("glint_training_exchange_intra_bytes_total",
         "exchange_intra_bytes_total",
         "Two-level exchange bytes attributed to the fast intra-node "
         "hop (exact fp32 local payloads)."),
        ("glint_training_exchange_inter_bytes_total",
         "exchange_inter_bytes_total",
         "Exchange bytes attributed to the slow inter-node hop "
         "(leaders-only quantized node payloads under the two-level "
         "topology; every byte of a flat round)."),
        ("glint_training_exchange_capacity_grows_total",
         "exchange_capacity_grows_total",
         "Adaptive capacity grow events (after an overflow spill)."),
        ("glint_training_exchange_capacity_shrinks_total",
         "exchange_capacity_shrinks_total",
         "Adaptive capacity shrink events (rolling high-water mark "
         "with 2x headroom hysteresis)."),
        ("glint_training_checkpoint_shards_skipped_total",
         "checkpoint_shards_skipped",
         "In-place checkpoint shard writes skipped because the shard "
         "was clean since the last committed save."),
    ]
    for name, key, help_ in counters:
        p.head(name, "counter", help_)
        p.sample(name, None, snap.get(key, 0))
    canary = snap.get("canary") or {}
    p.head("glint_canary_trips_total", "counter",
           "Divergence-canary trips this run.")
    p.sample("glint_canary_trips_total", None, canary.get("trips", 0))
    events = snap.get("events") or {}
    if events:
        p.head("glint_obs_events_recorded_total", "counter",
               "Span/instant events recorded by the event ring.")
        p.sample("glint_obs_events_recorded_total", None,
                 events.get("recorded", 0))
        p.head("glint_obs_events_dropped_total", "counter",
               "Events evicted from the bounded ring.")
        p.sample("glint_obs_events_dropped_total", None,
                 events.get("dropped", 0))
    steptime = (snap.get("steptime") or {}).get("phases") or {}
    if steptime:
        p.head("glint_training_steptime_seconds", "gauge",
               "Step-time attribution ledger: fit-thread wall seconds "
               "by phase (unattributed gap folded into 'other').")
        for phase, info in steptime.items():
            p.sample("glint_training_steptime_seconds",
                     {"phase": phase}, info.get("seconds"))
        p.head("glint_training_steptime_ops_total", "counter",
               "Accounted spans per ledger phase.")
        # Exemplar: the gang trace id the supervisor minted for this
        # generation (GLINT_TRACE_ID), so a steptime sample links back
        # to the merged gang trace it belongs to.
        trace_id = (snap.get("steptime") or {}).get("trace_id")
        for phase, info in steptime.items():
            p.sample("glint_training_steptime_ops_total",
                     {"phase": phase}, info.get("count", 0),
                     exemplar=({"trace_id": trace_id,
                                "value": info.get("seconds")}
                               if trace_id else None))
    stream = snap.get("streaming") or {}
    if stream:
        # Streaming-trainer gauges (ISSUE 10): present only on
        # fit_stream runs — batch fits keep their exposition unchanged.
        for name, key, help_ in [
            ("glint_stream_words_total", "words_streamed_total",
             "Kept (in-vocabulary) words consumed from the stream."),
            ("glint_stream_sentences_total", "sentences_streamed_total",
             "Sentences consumed from the stream."),
            ("glint_stream_oov_words_total", "oov_words_total",
             "Out-of-vocabulary occurrences routed to the candidate "
             "sketch."),
            ("glint_stream_promoted_words_total", "promoted_words_total",
             "Words promoted onto spare extra rows (online vocab "
             "growth)."),
            ("glint_stream_generations_published_total",
             "generations_published_total",
             "Committed model generations published to the serving "
             "fleet."),
        ]:
            p.head(name, "counter", help_)
            p.sample(name, None, stream.get(key, 0))
        for name, key, help_ in [
            ("glint_stream_vocab_size", "stream_vocab_size",
             "Grown vocabulary size (bootstrap base + promoted)."),
            ("glint_stream_extra_rows_free", "extra_rows_free",
             "Spare table rows still available for promotion."),
            ("glint_stream_sketch_fill", "sketch_fill",
             "Candidate-sketch occupancy fraction (1.0 = evicting)."),
            ("glint_stream_noise_drift_l1", "noise_drift_l1",
             "L1 distance between consecutive adaptive noise "
             "distributions at the last refresh."),
            ("glint_stream_lag_seconds", "stream_lag_seconds",
             "Wall seconds from a mini-epoch's first streamed sentence "
             "to its training completing (ingest-to-trained lag)."),
            ("glint_stream_last_publish_age_seconds",
             "last_publish_age_seconds",
             "Seconds since the last committed generation publish "
             "(NaN before any)."),
            ("glint_stream_buffer_fill", "buffer_fill",
             "Fill fraction of the last mini-epoch buffer."),
        ]:
            p.head(name, "gauge", help_)
            p.sample(name, None, stream.get(key))
    transform = snap.get("transform") or {}
    if transform:
        # Bulk-transform gauges (ISSUE 17): present only on
        # transform-file runs — training fits keep their exposition
        # unchanged.
        for name, key, help_ in [
            ("glint_transform_sentences_done_total",
             "sentences_done_total",
             "Sentences embedded into committed vector shards "
             "(resumed prefix included)."),
            ("glint_transform_shards_committed_total",
             "shards_committed_total",
             "Vector shards committed (payload + sidecar manifest + "
             "progress record) this run."),
            ("glint_transform_shards_skipped_total",
             "shards_skipped_total",
             "Committed shards verified and skipped by the resume "
             "scan."),
            ("glint_transform_post_warmup_compiles_total",
             "post_warmup_compiles_total",
             "Query-shape compiles after bulk warmup — nonzero means "
             "the warmed program family missed a steady-state shape."),
        ]:
            p.head(name, "counter", help_)
            p.sample(name, None, transform.get(key, 0))
        for name, key, help_ in [
            ("glint_transform_input_sentences", "input_sentences",
             "Input span size in sentences (lines)."),
            ("glint_transform_sentences_per_sec", "sentences_per_sec",
             "Rolling embedded-sentences/sec of this run (resumed "
             "prefix excluded)."),
            ("glint_transform_bucket_fill", "bucket_fill",
             "Real tokens over pow2-padded batch capacity (packing "
             "density of the dispatched blocks)."),
            ("glint_transform_producer_wait_seconds",
             "producer_wait_seconds",
             "Wall seconds the dispatch loop spent waiting on the "
             "producer thread (host-stall time)."),
            ("glint_transform_dispatch_seconds", "dispatch_seconds",
             "Wall seconds spent in device dispatch + host sync."),
        ]:
            p.head(name, "gauge", help_)
            p.sample(name, None, transform.get(key))
    slo = snap.get("slo") or {}
    if slo:
        # SLO burn-rate families (ISSUE 18): objectives + rolling-window
        # counts + derived burn rates from obs/slo.SloEngine, prefixed
        # per renderer so concatenated scrapes stay family-disjoint.
        slo_eps = slo.get("endpoints") or {}
        p.head("glint_training_slo_availability_target", "gauge",
               "Availability objective (success-ratio target) per "
               "tracked endpoint.")
        for ep, doc in slo_eps.items():
            p.sample("glint_training_slo_availability_target",
                     {"endpoint": ep}, doc.get("availability_target"))
        p.head("glint_training_slo_latency_target", "gauge",
               "Latency objective (fraction of good requests under the "
               "threshold) per tracked endpoint.")
        for ep, doc in slo_eps.items():
            p.sample("glint_training_slo_latency_target",
                     {"endpoint": ep}, doc.get("latency_target"))
        p.head("glint_training_slo_latency_threshold_ms", "gauge",
               "Latency SLI threshold in milliseconds per endpoint.")
        for ep, doc in slo_eps.items():
            p.sample("glint_training_slo_latency_threshold_ms",
                     {"endpoint": ep}, doc.get("latency_threshold_ms"))
        p.head("glint_training_slo_window_requests", "gauge",
               "Requests observed in each rolling SLO window.")
        for ep, doc in slo_eps.items():
            for win, w in (doc.get("windows") or {}).items():
                p.sample("glint_training_slo_window_requests",
                         {"endpoint": ep, "window": win},
                         w.get("total", 0))
        p.head("glint_training_slo_window_bad", "gauge",
               "SLI-violating requests in each rolling window, by SLI "
               "(availability = 5xx; latency = slower than threshold).")
        for ep, doc in slo_eps.items():
            for win, w in (doc.get("windows") or {}).items():
                p.sample("glint_training_slo_window_bad",
                         {"endpoint": ep, "sli": "availability",
                          "window": win}, w.get("bad_availability", 0))
                p.sample("glint_training_slo_window_bad",
                         {"endpoint": ep, "sli": "latency",
                          "window": win}, w.get("bad_latency", 0))
        p.head("glint_training_slo_burn_rate", "gauge",
               "Error-budget burn rate per SLI and window (1.0 = "
               "burning exactly the budget).")
        for ep, doc in slo_eps.items():
            burns = doc.get("burn_rates") or {}
            for win, rate in (burns.get("availability") or {}).items():
                p.sample("glint_training_slo_burn_rate",
                         {"endpoint": ep, "sli": "availability",
                          "window": win}, rate)
            for win, rate in (burns.get("latency") or {}).items():
                p.sample("glint_training_slo_burn_rate",
                         {"endpoint": ep, "sli": "latency",
                          "window": win}, rate)
        p.head("glint_training_slo_fast_burn", "gauge",
               "Multi-window fast-burn alert (5m AND 1h over 14.4x): "
               "page-severity budget burn.")
        for ep, doc in slo_eps.items():
            alerts = doc.get("alerts") or {}
            p.sample("glint_training_slo_fast_burn", {"endpoint": ep},
                     1 if alerts.get("fast_burn") else 0)
        p.head("glint_training_slo_slow_burn", "gauge",
               "Multi-window slow-burn alert (30m AND 6h over 6x): "
               "ticket-severity budget burn.")
        for ep, doc in slo_eps.items():
            alerts = doc.get("alerts") or {}
            p.sample("glint_training_slo_slow_burn", {"endpoint": ep},
                     1 if alerts.get("slow_burn") else 0)
    mem = snap.get("device_memory") or {}
    if mem:
        p.head("glint_device_memory_bytes", "gauge",
               "Per-device memory stats where the backend reports them.")
        for dev, stats in sorted(mem.items()):
            for stat, val in sorted(stats.items()):
                p.sample("glint_device_memory_bytes",
                         {"device": dev, "stat": stat}, val)
    return p.text()


# ----------------------------------------------------------------------
# Gang exposition (obs/aggregate.merge_training_snapshots)
# ----------------------------------------------------------------------


def gang_to_prometheus(snap: dict) -> str:
    """Render the merged gang snapshot as scrape-ready text: gang
    counters (sums of the per-rank values), total words/sec, the
    rank-skew straggler gauge, per-rank progress gauges, and the merged
    step-time attribution ledger. The caller appends the merged serving
    exposition when serving replicas joined the aggregate (distinct
    ``glint_serving_*`` names, so the concatenation stays lint-clean)."""
    p = _Prom()
    p.head("glint_gang_info", "gauge",
           "Gang metadata carried as labels; value is always 1.")
    p.sample("glint_gang_info", {"state": snap.get("state", "")}, 1)
    gauges = [
        ("glint_gang_generation", "generation",
         "Supervisor launch generation the merged view reflects."),
        ("glint_gang_num_workers", "num_workers",
         "Configured gang size."),
        ("glint_gang_ranks_reporting", "ranks_reporting",
         "Ranks with a current-generation heartbeat in the last sweep."),
        ("glint_gang_words_per_sec", "words_per_sec_total",
         "Sum of per-rank rolling trained-words/sec."),
        ("glint_gang_rank_skew", "rank_skew",
         "Straggler skew: max/median of per-rank mean step seconds "
         "(1.0 = balanced; NaN until ranks report step timing)."),
        ("glint_gang_checkpoint_shard_write_seconds",
         "checkpoint_shard_write_seconds_max",
         "Slowest rank's shard-block write seconds in the most recent "
         "checkpoint save (NaN before any)."),
        ("glint_gang_checkpoint_shard_verify_seconds",
         "checkpoint_shard_verify_seconds_max",
         "Slowest rank's per-shard manifest verify seconds in the most "
         "recent restore/stage (NaN before any)."),
    ]
    for name, key, help_ in gauges:
        p.head(name, "gauge", help_)
        p.sample(name, None, snap.get(key))
    counters = snap.get("counters") or {}
    # Full literal metric names (not f-string composed): graftlint's
    # prom-consistency rule checks every emitted name statically, and a
    # name it cannot resolve is a name nothing checks.
    for name, key, help_ in [
        ("glint_gang_steps_total", "steps_total",
         "Optimizer steps summed over ranks."),
        ("glint_gang_words_done_total", "words_done_total",
         "Trained words summed over ranks."),
        ("glint_gang_query_compiles_total", "query_compiles_total",
         "Engine query-shape compiles summed over ranks."),
        ("glint_gang_async_save_waits_total", "async_save_waits_total",
         "Checkpoint back-pressure waits summed over ranks."),
        ("glint_gang_exchange_bytes_total", "exchange_bytes_total",
         "Replica-exchange bytes on the wire summed over ranks."),
        ("glint_gang_exchange_rows_total", "exchange_rows_total",
         "Touched rows shipped through the exchange summed over ranks."),
        ("glint_gang_exchange_overflow_total", "exchange_overflow_total",
         "Capacity-overflow dense spills summed over ranks."),
        ("glint_gang_exchange_groups_total", "exchange_groups_total",
         "Dispatch groups folded into exchange rounds summed over "
         "ranks (coalescing rollup)."),
        ("glint_gang_exchange_intra_bytes_total",
         "exchange_intra_bytes_total",
         "Two-level intra-node hop bytes summed over ranks."),
        ("glint_gang_exchange_inter_bytes_total",
         "exchange_inter_bytes_total",
         "Slow-hop (inter-node / flat) exchange bytes summed over "
         "ranks."),
        ("glint_gang_checkpoint_shards_skipped_total",
         "checkpoint_shards_skipped_total",
         "Clean checkpoint shards skipped in-place summed over ranks."),
        ("glint_gang_canary_trips_total", "canary_trips_total",
         "Divergence-canary trips summed over ranks."),
        ("glint_gang_events_recorded_total", "events_recorded_total",
         "Obs events recorded summed over ranks."),
        ("glint_gang_events_dropped_total", "events_dropped_total",
         "Obs ring evictions summed over ranks."),
    ]:
        p.head(name, "counter", help_)
        p.sample(name, None, counters.get(key, 0))
    transform = snap.get("transform") or {}
    if transform:
        # Bulk-transform gang rollup (ISSUE 17): counters summed over
        # ranks, fill folded to the sparsest rank, producer wait to the
        # slowest.
        for name, key, help_ in [
            ("glint_gang_transform_sentences_done_total",
             "sentences_done_total",
             "Sentences embedded into committed shards summed over "
             "ranks."),
            ("glint_gang_transform_shards_committed_total",
             "shards_committed_total",
             "Vector shards committed summed over ranks."),
            ("glint_gang_transform_shards_skipped_total",
             "shards_skipped_total",
             "Resume-scan shard skips summed over ranks."),
            ("glint_gang_transform_post_warmup_compiles_total",
             "post_warmup_compiles_total",
             "Post-warmup query compiles summed over ranks (any "
             "nonzero value breaks the compile-once contract)."),
        ]:
            p.head(name, "counter", help_)
            p.sample(name, None, transform.get(key, 0))
        for name, key, help_ in [
            ("glint_gang_transform_input_sentences", "input_sentences",
             "Total input sentences across all rank spans."),
            ("glint_gang_transform_sentences_per_sec",
             "sentences_per_sec_total",
             "Sum of per-rank embedded-sentences/sec."),
            ("glint_gang_transform_bucket_fill_min", "bucket_fill_min",
             "Sparsest rank's packing density (real tokens over padded "
             "capacity)."),
            ("glint_gang_transform_producer_wait_seconds",
             "producer_wait_seconds_max",
             "Slowest rank's host-stall wait on the producer thread."),
        ]:
            p.head(name, "gauge", help_)
            p.sample(name, None, transform.get(key))
    per_rank = snap.get("per_rank") or {}
    p.head("glint_gang_rank_words_per_sec", "gauge",
           "Per-rank rolling trained-words/sec.")
    for rank, r in per_rank.items():
        p.sample("glint_gang_rank_words_per_sec", {"rank": rank},
                 r.get("words_per_sec_rolling"))
    p.head("glint_gang_rank_mean_step_seconds", "gauge",
           "Per-rank mean seconds per optimizer step (the rank_skew "
           "numerator/denominator population).")
    for rank, r in per_rank.items():
        p.sample("glint_gang_rank_mean_step_seconds", {"rank": rank},
                 r.get("mean_step_seconds"))
    p.head("glint_gang_rank_words_done", "gauge",
           "Per-rank trained-words counter.")
    for rank, r in per_rank.items():
        p.sample("glint_gang_rank_words_done", {"rank": rank},
                 r.get("words_done", 0))
    steptime = snap.get("steptime") or {}
    if steptime:
        p.head("glint_gang_steptime_seconds", "gauge",
               "Merged step-time attribution: fit-thread wall seconds "
               "by phase, summed over ranks.")
        for phase, info in steptime.items():
            p.sample("glint_gang_steptime_seconds", {"phase": phase},
                     info.get("seconds"))
        p.head("glint_gang_steptime_span_seconds", "summary",
               "Merged per-span duration quantiles by ledger phase "
               "(bucket-exact cross-rank histogram merge).")
        for phase, info in steptime.items():
            if "p50_ms" not in info:
                continue
            for q, key in (("0.5", "p50_ms"), ("0.95", "p95_ms"),
                           ("0.99", "p99_ms")):
                p.sample("glint_gang_steptime_span_seconds",
                         {"phase": phase, "quantile": q},
                         info[key] / 1e3)
            # span_seconds, not the phase total: the phase total folds
            # the unattributed wall gap into "other", which would make
            # sum/count disagree with this summary's own quantiles.
            p.sample("glint_gang_steptime_span_seconds_sum",
                     {"phase": phase},
                     info.get("span_seconds", info.get("seconds")))
            # Exemplar: the supervisor-minted gang trace id (first rank
            # reporting one), linking the merged summary to the merged
            # gang trace.
            trace_id = snap.get("steptime_trace_id")
            p.sample("glint_gang_steptime_span_seconds_count",
                     {"phase": phase}, info.get("count", 0),
                     exemplar=({"trace_id": trace_id,
                                "value": info.get("span_seconds")}
                               if trace_id else None))
    slo = snap.get("slo") or {}
    if slo:
        # SLO burn-rate families (ISSUE 18) over the fleet-merged SLO
        # snapshot the gang aggregator lifted from its serving scrape
        # (gang-prefixed: this exposition is concatenated with the
        # serving one, and families in one scrape must be disjoint).
        slo_eps = slo.get("endpoints") or {}
        p.head("glint_gang_slo_availability_target", "gauge",
               "Availability objective (success-ratio target) per "
               "tracked endpoint, fleet-merged view.")
        for ep, doc in slo_eps.items():
            p.sample("glint_gang_slo_availability_target",
                     {"endpoint": ep}, doc.get("availability_target"))
        p.head("glint_gang_slo_latency_target", "gauge",
               "Latency objective (fraction of good requests under the "
               "threshold) per tracked endpoint, fleet-merged view.")
        for ep, doc in slo_eps.items():
            p.sample("glint_gang_slo_latency_target",
                     {"endpoint": ep}, doc.get("latency_target"))
        p.head("glint_gang_slo_latency_threshold_ms", "gauge",
               "Latency SLI threshold in milliseconds per endpoint.")
        for ep, doc in slo_eps.items():
            p.sample("glint_gang_slo_latency_threshold_ms",
                     {"endpoint": ep}, doc.get("latency_threshold_ms"))
        p.head("glint_gang_slo_window_requests", "gauge",
               "Requests observed in each rolling SLO window, summed "
               "over replicas.")
        for ep, doc in slo_eps.items():
            for win, w in (doc.get("windows") or {}).items():
                p.sample("glint_gang_slo_window_requests",
                         {"endpoint": ep, "window": win},
                         w.get("total", 0))
        p.head("glint_gang_slo_window_bad", "gauge",
               "SLI-violating requests in each rolling window, by SLI, "
               "summed over replicas.")
        for ep, doc in slo_eps.items():
            for win, w in (doc.get("windows") or {}).items():
                p.sample("glint_gang_slo_window_bad",
                         {"endpoint": ep, "sli": "availability",
                          "window": win}, w.get("bad_availability", 0))
                p.sample("glint_gang_slo_window_bad",
                         {"endpoint": ep, "sli": "latency",
                          "window": win}, w.get("bad_latency", 0))
        p.head("glint_gang_slo_burn_rate", "gauge",
               "Error-budget burn rate per SLI and window over the "
               "merged fleet traffic (1.0 = burning exactly the "
               "budget).")
        for ep, doc in slo_eps.items():
            burns = doc.get("burn_rates") or {}
            for win, rate in (burns.get("availability") or {}).items():
                p.sample("glint_gang_slo_burn_rate",
                         {"endpoint": ep, "sli": "availability",
                          "window": win}, rate)
            for win, rate in (burns.get("latency") or {}).items():
                p.sample("glint_gang_slo_burn_rate",
                         {"endpoint": ep, "sli": "latency",
                          "window": win}, rate)
        p.head("glint_gang_slo_fast_burn", "gauge",
               "Fleet-level multi-window fast-burn alert (5m AND 1h "
               "over 14.4x).")
        for ep, doc in slo_eps.items():
            alerts = doc.get("alerts") or {}
            p.sample("glint_gang_slo_fast_burn", {"endpoint": ep},
                     1 if alerts.get("fast_burn") else 0)
        p.head("glint_gang_slo_slow_burn", "gauge",
               "Fleet-level multi-window slow-burn alert (30m AND 6h "
               "over 6x).")
        for ep, doc in slo_eps.items():
            alerts = doc.get("alerts") or {}
            p.sample("glint_gang_slo_slow_burn", {"endpoint": ep},
                     1 if alerts.get("slow_burn") else 0)
    # Per-model fleet rollup (ISSUE 20), lifted from the scraped
    # serving replicas' merged catalog view (gang-prefixed: this
    # exposition is concatenated with the full serving one, which
    # carries the detailed glint_model_* families).
    gmodels = (snap.get("serving") or {}).get("models") or {}
    if gmodels:
        p.head("glint_gang_model_requests_total", "counter",
               "Requests per catalog model summed over endpoints and "
               "serving replicas.")
        for mid, m in sorted(gmodels.items()):
            p.sample("glint_gang_model_requests_total", {"model": mid},
                     sum(int(ep.get("count") or 0)
                         for ep in (m.get("endpoints") or {}).values()))
        p.head("glint_gang_model_resident_replicas", "gauge",
               "Serving replicas holding this model's tables on "
               "device.")
        for mid, m in sorted(gmodels.items()):
            p.sample("glint_gang_model_resident_replicas",
                     {"model": mid}, m.get("resident_replicas", 0))
        p.head("glint_gang_model_table_swaps_total", "counter",
               "Per-model hot-swaps summed over serving replicas.")
        for mid, m in sorted(gmodels.items()):
            hs = m.get("hot_swap") or {}
            p.sample("glint_gang_model_table_swaps_total",
                     {"model": mid}, hs.get("table_swaps_total", 0))
        p.head("glint_gang_model_generation_info", "gauge",
               "Served generation per catalog model across the fleet "
               "('mixed' while a per-model rollout is in flight); "
               "value is always 1.")
        for mid, m in sorted(gmodels.items()):
            hs = m.get("hot_swap") or {}
            p.sample("glint_gang_model_generation_info",
                     {"model": mid,
                      "generation": hs.get("generation") or ""}, 1)
    return p.text()


# ----------------------------------------------------------------------
# Serving exposition (utils/metrics.ServingMetrics.snapshot)
# ----------------------------------------------------------------------


def serving_to_prometheus(snap: dict) -> str:
    """Render a ServingMetrics snapshot as scrape-ready text: request and
    error counters per endpoint, a latency summary (the histogram's
    p50/p95/p99), the coalesced-batch-size histogram, cache counters,
    and the compile accounting the PR-2 zero-compile contract watches."""
    p = _Prom()
    endpoints = snap.get("endpoints", {})
    p.head("glint_serving_requests_total", "counter",
           "Requests observed per endpoint path.")
    for path, ep in endpoints.items():
        p.sample("glint_serving_requests_total", {"path": path}, ep["count"])
    p.head("glint_serving_request_errors_total", "counter",
           "Responses with status >= 400 per endpoint path.")
    for path, ep in endpoints.items():
        p.sample("glint_serving_request_errors_total", {"path": path},
                 ep["errors"])
    p.head("glint_serving_request_latency_seconds", "summary",
           "Per-endpoint request latency quantiles.")
    for path, ep in endpoints.items():
        for q, key in (("0.5", "p50_ms"), ("0.95", "p95_ms"),
                       ("0.99", "p99_ms")):
            p.sample("glint_serving_request_latency_seconds",
                     {"path": path, "quantile": q}, ep[key] / 1e3)
        p.sample("glint_serving_request_latency_seconds_sum",
                 {"path": path}, ep["mean_ms"] * ep["count"] / 1e3)
        # Exemplar: the last kept request trace on this endpoint, so a
        # latency quantile on a dashboard links to a concrete trace.
        ex = ep.get("exemplar") or {}
        p.sample("glint_serving_request_latency_seconds_count",
                 {"path": path}, ep["count"],
                 exemplar=({"trace_id": ex.get("trace_id"),
                            "value": (ex.get("value_ms") or 0) / 1e3}
                           if ex.get("trace_id") else None))
    sizes = {int(k): int(v)
             for k, v in snap.get("coalesced_batch_sizes", {}).items()}
    p.head("glint_serving_coalesced_batch_size", "histogram",
           "Queries per coalesced device dispatch.")
    cum, total = 0, 0
    for size in sorted(sizes):
        cum += sizes[size]
        total += size * sizes[size]
        p.sample("glint_serving_coalesced_batch_size_bucket",
                 {"le": str(size)}, cum)
    p.sample("glint_serving_coalesced_batch_size_bucket", {"le": "+Inf"}, cum)
    p.sample("glint_serving_coalesced_batch_size_sum", None, total)
    p.sample("glint_serving_coalesced_batch_size_count", None, cum)
    over = snap.get("overload", {})
    p.head("glint_serving_shed_total", "counter",
           "Requests shed with 429, by reason (admission = in-flight "
           "high-water mark; degraded = cache-only mode).")
    p.sample("glint_serving_shed_total", {"reason": "admission"},
             over.get("shed_admission_total", 0))
    p.sample("glint_serving_shed_total", {"reason": "degraded"},
             over.get("shed_degraded_total", 0))
    p.head("glint_serving_deadline_hits_total", "counter",
           "Requests answered 504: deadline passed before the device.")
    p.sample("glint_serving_deadline_hits_total", None,
             over.get("deadline_504_total", 0))
    p.head("glint_serving_degraded_entered_total", "counter",
           "Transitions into degraded cache-only mode.")
    p.sample("glint_serving_degraded_entered_total", None,
             over.get("degraded_entered_total", 0))
    p.head("glint_serving_inflight_peak", "gauge",
           "Peak admitted device-touching requests in flight.")
    p.sample("glint_serving_inflight_peak", None,
             over.get("inflight_peak", 0))
    cache = snap.get("synonym_cache", {})
    p.head("glint_serving_cache_hits_total", "counter",
           "Synonym result-cache hits.")
    p.sample("glint_serving_cache_hits_total", None, cache.get("hits", 0))
    p.head("glint_serving_cache_misses_total", "counter",
           "Synonym result-cache misses.")
    p.sample("glint_serving_cache_misses_total", None, cache.get("misses", 0))
    compiles = snap.get("compiles", {})
    p.head("glint_serving_compiles_total", "counter",
           "Query-op shapes jit-compiled since engine construction.")
    p.sample("glint_serving_compiles_total", None, compiles.get("total", 0))
    p.head("glint_serving_post_warmup_compiles", "gauge",
           "Compiles past serving warmup (the zero-compile contract).")
    p.sample("glint_serving_post_warmup_compiles", None,
             compiles.get("post_warmup", 0))
    swap = snap.get("hot_swap") or {}
    p.head("glint_serving_table_swaps_total", "counter",
           "Table generations hot-swapped into the live engine.")
    p.sample("glint_serving_table_swaps_total", None,
             swap.get("table_swaps_total", 0))
    p.head("glint_serving_swap_failures_total", "counter",
           "Hot-swap attempts that failed verification or staging "
           "(the previous generation stayed live).")
    p.sample("glint_serving_swap_failures_total", None,
             swap.get("swap_failures_total", 0))
    p.head("glint_serving_watch_errors_total", "counter",
           "Transient publish-dir read errors the snapshot watcher "
           "absorbed (backed off, retried on a later poll; the "
           "generation was NOT marked failed).")
    p.sample("glint_serving_watch_errors_total", None,
             swap.get("watch_errors_total", 0))
    p.head("glint_serving_last_swap_age_seconds", "gauge",
           "Seconds since the last successful hot-swap (NaN before "
           "any).")
    p.sample("glint_serving_last_swap_age_seconds", None,
             swap.get("last_swap_age_seconds"))
    p.head("glint_serving_generation_info", "gauge",
           "Served snapshot generation carried as a label; value is "
           "always 1.")
    p.sample("glint_serving_generation_info",
             {"generation": swap.get("generation") or ""}, 1)
    ck = snap.get("checkpoint") or {}
    p.head("glint_serving_pending_async_saves", "gauge",
           "Async table snapshots in flight on the served engine.")
    p.sample("glint_serving_pending_async_saves", None,
             ck.get("pending_async_saves", 0))
    p.head("glint_serving_checkpoint_write_seconds", "gauge",
           "Wall seconds of the engine's most recent snapshot write.")
    p.sample("glint_serving_checkpoint_write_seconds", None,
             ck.get("checkpoint_write_seconds"))
    p.head("glint_serving_last_checkpoint_age_seconds", "gauge",
           "Seconds since the engine last committed a table snapshot "
           "(NaN when it never has).")
    p.sample("glint_serving_last_checkpoint_age_seconds", None,
             ck.get("last_checkpoint_age_seconds"))
    # ANN index family (ISSUE 12): the serving-side view of the
    # two-stage approximate top-k — build/refresh lifecycle, the
    # measured recall gate, and per-query probe accounting.
    index = snap.get("index") or {}
    p.head("glint_index_enabled", "gauge",
           "Whether a device-resident ANN index is built for this "
           "server (1) or every query is exact (0).")
    p.sample("glint_index_enabled", None,
             1 if index.get("enabled") else 0)
    p.head("glint_index_clusters", "gauge",
           "Coarse k-means clusters in the ANN index.")
    p.sample("glint_index_clusters", None, index.get("clusters"))
    p.head("glint_index_nprobe", "gauge",
           "Clusters probed per approximate query.")
    p.sample("glint_index_nprobe", None, index.get("nprobe"))
    p.head("glint_index_build_seconds", "gauge",
           "Wall seconds of the most recent index build/refresh.")
    p.sample("glint_index_build_seconds", None,
             index.get("build_seconds"))
    p.head("glint_index_last_refresh_age_seconds", "gauge",
           "Seconds since the index was last built or refreshed (NaN "
           "before any build).")
    p.sample("glint_index_last_refresh_age_seconds", None,
             index.get("last_refresh_age_seconds"))
    p.head("glint_index_refreshes_total", "counter",
           "Index builds/refreshes (boot + one per hot-swap).")
    p.sample("glint_index_refreshes_total", None,
             index.get("refreshes_total", 0))
    p.head("glint_index_recall_at10", "gauge",
           "Measured recall@10 of the approximate path vs the exact "
           "path on the same tables (NaN before any measurement).")
    p.sample("glint_index_recall_at10", None, index.get("recall_at10"))
    p.head("glint_index_recall_gate_ok", "gauge",
           "Whether the last recall measurement cleared the gate (a "
           "failing gate holds the exact path live).")
    p.sample("glint_index_recall_gate_ok", None,
             1 if index.get("recall_gate_ok") else 0)
    p.head("glint_index_probes_per_query", "gauge",
           "Mean clusters probed per approximate query (NaN before "
           "any approximate query).")
    p.sample("glint_index_probes_per_query", None,
             index.get("probes_per_query"))
    p.head("glint_index_ann_queries_total", "counter",
           "Synonym queries answered through the ANN index.")
    p.sample("glint_index_ann_queries_total", None,
             index.get("ann_queries_total", 0))
    p.head("glint_index_probes_total", "counter",
           "Total clusters probed across all approximate queries.")
    p.sample("glint_index_probes_total", None,
             index.get("probes_total", 0))
    p.head("glint_index_exact_fallbacks_total", "counter",
           "Queries served by the exact path while the index is "
           "enabled, by reason (requested = per-request exact=true; "
           "gate = recall gate holding the approximate path back).")
    for reason, n in sorted((index.get("exact_fallbacks") or {}).items()):
        p.sample("glint_index_exact_fallbacks_total",
                 {"reason": reason}, n)
    p.head("glint_index_table_versions_behind", "gauge",
           "Table mutations since the index was built against the "
           "live tables (staleness; NaN without an index).")
    p.sample("glint_index_table_versions_behind", None,
             index.get("table_versions_behind"))
    slo = snap.get("slo") or {}
    if slo:
        # SLO burn-rate families (ISSUE 18): per-endpoint objectives,
        # rolling-window good/bad counts, and the multi-window burn
        # rates + alerts from obs/slo.SloEngine. Rendered only when a
        # SLO engine is attached, so bare ServingMetrics snapshots keep
        # their exposition unchanged.
        slo_eps = slo.get("endpoints") or {}
        p.head("glint_slo_availability_target", "gauge",
               "Availability objective (success-ratio target) per "
               "tracked endpoint.")
        for ep, doc in slo_eps.items():
            p.sample("glint_slo_availability_target",
                     {"endpoint": ep}, doc.get("availability_target"))
        p.head("glint_slo_latency_target", "gauge",
               "Latency objective (fraction of good requests under the "
               "threshold) per tracked endpoint.")
        for ep, doc in slo_eps.items():
            p.sample("glint_slo_latency_target",
                     {"endpoint": ep}, doc.get("latency_target"))
        p.head("glint_slo_latency_threshold_ms", "gauge",
               "Latency SLI threshold in milliseconds per endpoint.")
        for ep, doc in slo_eps.items():
            p.sample("glint_slo_latency_threshold_ms",
                     {"endpoint": ep}, doc.get("latency_threshold_ms"))
        p.head("glint_slo_window_requests", "gauge",
               "Requests observed in each rolling SLO window.")
        for ep, doc in slo_eps.items():
            for win, w in (doc.get("windows") or {}).items():
                p.sample("glint_slo_window_requests",
                         {"endpoint": ep, "window": win},
                         w.get("total", 0))
        p.head("glint_slo_window_bad", "gauge",
               "SLI-violating requests in each rolling window, by SLI "
               "(availability = 5xx; latency = slower than threshold).")
        for ep, doc in slo_eps.items():
            for win, w in (doc.get("windows") or {}).items():
                p.sample("glint_slo_window_bad",
                         {"endpoint": ep, "sli": "availability",
                          "window": win}, w.get("bad_availability", 0))
                p.sample("glint_slo_window_bad",
                         {"endpoint": ep, "sli": "latency",
                          "window": win}, w.get("bad_latency", 0))
        p.head("glint_slo_burn_rate", "gauge",
               "Error-budget burn rate per SLI and window (1.0 = "
               "burning exactly the budget; 14.4 = the fast-burn page "
               "threshold).")
        for ep, doc in slo_eps.items():
            burns = doc.get("burn_rates") or {}
            for win, rate in (burns.get("availability") or {}).items():
                p.sample("glint_slo_burn_rate",
                         {"endpoint": ep, "sli": "availability",
                          "window": win}, rate)
            for win, rate in (burns.get("latency") or {}).items():
                p.sample("glint_slo_burn_rate",
                         {"endpoint": ep, "sli": "latency",
                          "window": win}, rate)
        p.head("glint_slo_fast_burn", "gauge",
               "Multi-window fast-burn alert (5m AND 1h windows both "
               "over 14.4x): page-severity budget burn.")
        for ep, doc in slo_eps.items():
            alerts = doc.get("alerts") or {}
            p.sample("glint_slo_fast_burn", {"endpoint": ep},
                     1 if alerts.get("fast_burn") else 0)
        p.head("glint_slo_slow_burn", "gauge",
               "Multi-window slow-burn alert (30m AND 6h windows both "
               "over 6x): ticket-severity budget burn.")
        for ep, doc in slo_eps.items():
            alerts = doc.get("alerts") or {}
            p.sample("glint_slo_slow_burn", {"endpoint": ep},
                     1 if alerts.get("slow_burn") else 0)
    # Per-model catalog families (ISSUE 20): the same snapshot shape
    # as the top level, one block per catalog model, labeled {model}.
    # Rendered only for multi-model servers/fleets, so single-model
    # expositions are byte-identical to the pre-catalog format.
    models = snap.get("models") or {}
    if models:
        p.head("glint_model_requests_total", "counter",
               "Requests observed per catalog model and endpoint "
               "path.")
        for mid, m in sorted(models.items()):
            for path, ep in (m.get("endpoints") or {}).items():
                p.sample("glint_model_requests_total",
                         {"model": mid, "path": path}, ep["count"])
        p.head("glint_model_request_errors_total", "counter",
               "Responses with status >= 400 per catalog model and "
               "endpoint path.")
        for mid, m in sorted(models.items()):
            for path, ep in (m.get("endpoints") or {}).items():
                p.sample("glint_model_request_errors_total",
                         {"model": mid, "path": path}, ep["errors"])
        p.head("glint_model_cache_hits_total", "counter",
               "Synonym result-cache hits per catalog model (each "
               "model's cache is private — a cross-model hit is "
               "structurally impossible).")
        for mid, m in sorted(models.items()):
            c = m.get("synonym_cache") or {}
            p.sample("glint_model_cache_hits_total", {"model": mid},
                     c.get("hits", 0))
        p.head("glint_model_cache_misses_total", "counter",
               "Synonym result-cache misses per catalog model.")
        for mid, m in sorted(models.items()):
            c = m.get("synonym_cache") or {}
            p.sample("glint_model_cache_misses_total", {"model": mid},
                     c.get("misses", 0))
        p.head("glint_model_post_warmup_compiles", "gauge",
               "Compiles past this model's load warmup (0 proves "
               "shape-keyed program sharing: a same-shape model "
               "reuses every warmed program).")
        for mid, m in sorted(models.items()):
            comp = m.get("compiles") or {}
            p.sample("glint_model_post_warmup_compiles",
                     {"model": mid}, comp.get("post_warmup", 0))
        p.head("glint_model_table_swaps_total", "counter",
               "Generations hot-swapped per catalog model (a "
               "per-model rollout moves exactly one model's count).")
        for mid, m in sorted(models.items()):
            hs = m.get("hot_swap") or {}
            p.sample("glint_model_table_swaps_total", {"model": mid},
                     hs.get("table_swaps_total", 0))
        p.head("glint_model_swap_failures_total", "counter",
               "Failed hot-swap attempts per catalog model.")
        for mid, m in sorted(models.items()):
            hs = m.get("hot_swap") or {}
            p.sample("glint_model_swap_failures_total", {"model": mid},
                     hs.get("swap_failures_total", 0))
        p.head("glint_model_generation_info", "gauge",
               "Served generation per catalog model carried as a "
               "label; value is always 1.")
        for mid, m in sorted(models.items()):
            hs = m.get("hot_swap") or {}
            p.sample("glint_model_generation_info",
                     {"model": mid,
                      "generation": hs.get("generation") or ""}, 1)
        p.head("glint_model_resident_replicas", "gauge",
               "Replicas holding this model's tables on device (a "
               "single server reports 0 or 1; LRU stage-out drops "
               "it).")
        for mid, m in sorted(models.items()):
            p.sample("glint_model_resident_replicas", {"model": mid},
                     m.get("resident_replicas", 0))
        p.head("glint_model_resident_bytes", "gauge",
               "Device bytes this model's resident tables occupy "
               "(0 while staged out).")
        for mid, m in sorted(models.items()):
            p.sample("glint_model_resident_bytes", {"model": mid},
                     m.get("resident_bytes", 0))
        p.head("glint_model_pinned", "gauge",
               "Whether the model is pinned against LRU eviction "
               "(default model, mid-rollout holds).")
        for mid, m in sorted(models.items()):
            p.sample("glint_model_pinned", {"model": mid},
                     1 if m.get("pinned") else 0)
        p.head("glint_model_stage_ins_total", "counter",
               "Times this model's tables were staged back onto the "
               "device after an eviction.")
        for mid, m in sorted(models.items()):
            p.sample("glint_model_stage_ins_total", {"model": mid},
                     m.get("stage_ins_total", 0))
        p.head("glint_model_evictions_total", "counter",
               "Times this model's tables were staged out under "
               "memory-budget pressure.")
        for mid, m in sorted(models.items()):
            p.sample("glint_model_evictions_total", {"model": mid},
                     m.get("evictions_total", 0))
    cat = snap.get("catalog") or {}
    if cat:
        p.head("glint_catalog_models", "gauge",
               "Models installed in the serving catalog.")
        p.sample("glint_catalog_models", None, cat.get("models", 0))
        p.head("glint_catalog_resident_models", "gauge",
               "Catalog models currently resident on device (summed "
               "across replicas in the fleet view).")
        p.sample("glint_catalog_resident_models", None,
                 cat.get("resident_models", 0))
        p.head("glint_catalog_budget_bytes", "gauge",
               "Configured device-memory budget for resident tables "
               "(NaN when unbounded).")
        p.sample("glint_catalog_budget_bytes", None,
                 cat.get("budget_bytes"))
        p.head("glint_catalog_resident_bytes", "gauge",
               "Device bytes all resident catalog tables occupy.")
        p.sample("glint_catalog_resident_bytes", None,
                 cat.get("resident_bytes", 0))
        p.head("glint_catalog_evictions_total", "counter",
               "LRU stage-outs forced by the memory budget.")
        p.sample("glint_catalog_evictions_total", None,
                 cat.get("evictions_total", 0))
        p.head("glint_catalog_stage_ins_total", "counter",
               "Cold models staged back onto the device.")
        p.sample("glint_catalog_stage_ins_total", None,
                 cat.get("stage_ins_total", 0))
        p.head("glint_catalog_stage_in_seconds_total", "counter",
               "Wall seconds spent staging evicted models back in "
               "(off the request path; requests queue, never 500).")
        p.sample("glint_catalog_stage_in_seconds_total", None,
                 cat.get("stage_in_seconds_total", 0))
        p.head("glint_catalog_cold_hits_total", "counter",
               "Requests that arrived while their model was staged "
               "out (each waited for the stage-in, then served).")
        p.sample("glint_catalog_cold_hits_total", None,
                 cat.get("cold_hits_total", 0))
        p.head("glint_catalog_query_program_builds_total", "counter",
               "Process-level query program builds — flat across "
               "same-shape model loads (the sharing proof).")
        p.sample("glint_catalog_query_program_builds_total", None,
                 cat.get("query_program_builds", 0))
        p.head("glint_catalog_shared_program_hits_total", "counter",
               "Engine program lookups answered by another engine's "
               "compiled program.")
        p.sample("glint_catalog_shared_program_hits_total", None,
                 cat.get("shared_program_hits", 0))
    return p.text()


# ----------------------------------------------------------------------
# Fleet balancer exposition (fleet.LoadBalancer.metrics_doc)
# ----------------------------------------------------------------------


def fleet_to_prometheus(doc: dict) -> str:
    """Render a fleet balancer document: replica liveness and proxy
    accounting per replica, the balancer's retry/exhaustion counters,
    and each replica's index recall gauges (fleet-prefixed and labeled
    by replica — this text is concatenated with
    ``serving_to_prometheus`` over the merged ``fleet`` member, and
    families in one scrape must be disjoint). One scrape of the
    balancer therefore carries the fleet totals AND the per-replica
    recall-gate states."""
    p = _Prom()
    replicas = doc.get("replicas") or []
    p.head("glint_fleet_replicas", "gauge",
           "Serving replicas configured behind the balancer.")
    p.sample("glint_fleet_replicas", None, len(replicas))
    p.head("glint_fleet_replica_up", "gauge",
           "Whether the replica answered the last metrics scrape.")
    for r in replicas:
        p.sample("glint_fleet_replica_up", {"replica": r.get("url", "")},
                 1 if r.get("up") else 0)
    p.head("glint_fleet_proxied_total", "counter",
           "Requests the balancer forwarded, by replica.")
    for r in replicas:
        p.sample("glint_fleet_proxied_total",
                 {"replica": r.get("url", "")},
                 r.get("proxied_total", 0))
    p.head("glint_fleet_proxy_errors_total", "counter",
           "Forward attempts that failed at the connection level, by "
           "replica.")
    for r in replicas:
        p.sample("glint_fleet_proxy_errors_total",
                 {"replica": r.get("url", "")},
                 r.get("proxy_errors_total", 0))
    # Circuit breaker (ISSUE 14): per-replica state machine driven by
    # the active health prober and the data plane's own connection
    # verdicts — an ejected (open) replica costs zero client latency.
    p.head("glint_fleet_breaker_state", "gauge",
           "Per-replica circuit-breaker state: 1 on the row matching "
           "the current state (closed replicas receive traffic, open "
           "ones are ejected from rotation, half-open ones serve "
           "prober trials only).")
    for r in replicas:
        br = r.get("breaker") or {}
        for st in ("closed", "open", "half_open"):
            p.sample("glint_fleet_breaker_state",
                     {"replica": r.get("url", ""), "state": st},
                     1 if br.get("state") == st else 0)
    p.head("glint_fleet_breaker_held", "gauge",
           "Whether the replica is administratively held out of "
           "rotation (rollout drain / canary staging).")
    for r in replicas:
        br = r.get("breaker") or {}
        p.sample("glint_fleet_breaker_held",
                 {"replica": r.get("url", "")},
                 1 if br.get("held") else 0)
    for name, key, help_ in [
        ("glint_fleet_breaker_opened_total", "opened_total",
         "Closed -> open transitions (consecutive-failure ejections)."),
        ("glint_fleet_breaker_reopened_total", "reopened_total",
         "Half-open trial failures that re-opened the breaker."),
        ("glint_fleet_breaker_closed_total", "closed_total",
         "Half-open -> closed readmissions after the success "
         "threshold."),
        ("glint_fleet_probe_failures_total", "probe_failures_total",
         "Active health probes that failed (connect error, non-200, "
         "or a generation-handshake mismatch)."),
        ("glint_fleet_probe_successes_total", "probe_successes_total",
         "Active health probes answered healthy."),
    ]:
        p.head(name, "counter", help_)
        for r in replicas:
            br = r.get("breaker") or {}
            p.sample(name, {"replica": r.get("url", "")},
                     br.get(key, 0))
    bal = doc.get("balancer") or {}
    p.head("glint_fleet_shed_retries_total", "counter",
           "Requests retried on another replica after a 429/503 shed "
           "(the replicas' own backpressure steering the spread).")
    p.sample("glint_fleet_shed_retries_total", None,
             bal.get("shed_retries_total", 0))
    p.head("glint_fleet_exhausted_total", "counter",
           "Requests every replica shed or failed — the shed response "
           "was relayed to the client.")
    p.sample("glint_fleet_exhausted_total", None,
             bal.get("exhausted_total", 0))
    p.head("glint_fleet_breaker_skips_total", "counter",
           "Replica attempts avoided because the breaker was open or "
           "held — each one a timeout a client did not pay.")
    p.sample("glint_fleet_breaker_skips_total", None,
             bal.get("breaker_skips_total", 0))
    p.head("glint_fleet_restart_retries_total", "counter",
           "Connection-refused attempts retried with jittered backoff "
           "inside a known replica-restart window.")
    p.sample("glint_fleet_restart_retries_total", None,
             bal.get("restart_retries_total", 0))
    # Fleet supervisor (ISSUE 14): replica relaunch accounting.
    sup = doc.get("supervisor") or {}
    p.head("glint_fleet_restarts_total", "counter",
           "Replica relaunches by the fleet supervisor (crash or "
           "hung-probe kill), all replicas.")
    p.sample("glint_fleet_restarts_total", None,
             sup.get("restarts_total", 0))
    p.head("glint_fleet_replicas_failed", "gauge",
           "Replicas whose restart budget is exhausted (left down; "
           "the fleet serves from the survivors).")
    p.sample("glint_fleet_replicas_failed", None,
             sup.get("replicas_failed", 0))
    p.head("glint_fleet_replica_restarts_total", "counter",
           "Relaunches per replica slot.")
    for rs in sup.get("replica_states") or []:
        p.sample("glint_fleet_replica_restarts_total",
                 {"replica": str(rs.get("replica", ""))},
                 rs.get("restarts", 0))
    p.head("glint_fleet_replica_state_info", "gauge",
           "Fleet-supervisor state per replica slot carried as a "
           "label; value is always 1.")
    for rs in sup.get("replica_states") or []:
        p.sample("glint_fleet_replica_state_info",
                 {"replica": str(rs.get("replica", "")),
                  "state": rs.get("state", "")}, 1)
    # Rolling rollout + shadow canary (ISSUE 14).
    ro = doc.get("rollout") or {}
    for name, key, help_ in [
        ("glint_fleet_rollouts_started_total", "rollouts_started_total",
         "Generation rollouts the coordinator started."),
        ("glint_fleet_rollouts_completed_total",
         "rollouts_completed_total",
         "Rollouts that promoted the generation to every replica."),
        ("glint_fleet_rollouts_halted_total", "rollouts_halted_total",
         "Rollouts halted mid-way (replica unavailable — retried once "
         "the fleet is whole again)."),
        ("glint_fleet_rollout_steps_total", "rollout_steps_total",
         "Per-replica swap steps performed across all rollouts."),
        ("glint_fleet_generations_failed_total",
         "generations_failed_total",
         "Candidate generations whose staging failed on a replica "
         "(not retried until the pointer moves)."),
        ("glint_fleet_watch_errors_total", "watch_errors_total",
         "Transient publish-pointer read errors the rollout "
         "coordinator absorbed."),
    ]:
        p.head(name, "counter", help_)
        p.sample(name, None, ro.get(key, 0))
    p.head("glint_fleet_rollout_in_progress", "gauge",
           "Whether a rolling generation rollout is currently "
           "executing.")
    p.sample("glint_fleet_rollout_in_progress", None,
             1 if ro.get("in_progress") else 0)
    p.head("glint_fleet_generation_info", "gauge",
           "Fleet-promoted generation carried as a label; value is "
           "always 1.")
    p.sample("glint_fleet_generation_info",
             {"generation": ro.get("generation") or ""}, 1)
    can = ro.get("canary") or {}
    p.head("glint_fleet_canary_evaluations_total", "counter",
           "Shadow-canary evaluations run against candidate "
           "generations.")
    p.sample("glint_fleet_canary_evaluations_total", None,
             can.get("evaluations_total", 0))
    p.head("glint_fleet_canary_holdbacks_total", "counter",
           "Candidate generations held back by the canary gate "
           "(regression: the rollout never proceeded; the live "
           "generation kept serving everywhere).")
    p.sample("glint_fleet_canary_holdbacks_total", None,
             can.get("holdbacks_total", 0))
    p.head("glint_fleet_canary_last_agreement", "gauge",
           "Mean top-k agreement of the last canary evaluation "
           "against the live fleet (NaN before any evaluation).")
    p.sample("glint_fleet_canary_last_agreement", None,
             can.get("last_agreement"))
    p.head("glint_fleet_canary_agreement_gate", "gauge",
           "Agreement threshold a candidate must clear to promote.")
    p.sample("glint_fleet_canary_agreement_gate", None,
             can.get("agreement_gate"))
    p.head("glint_fleet_canary_last_scored", "gauge",
           "Mirrored + probe responses scored in the last canary "
           "evaluation.")
    p.sample("glint_fleet_canary_last_scored", None,
             can.get("last_scored", 0))
    # Per-replica index recall: the fleet view of the ISSUE 12 recall
    # gate (fleet-prefixed names — this exposition is concatenated
    # with serving_to_prometheus over the merged doc, and families in
    # one scrape must be disjoint).
    p.head("glint_fleet_index_recall_at10", "gauge",
           "Per-replica measured recall@10 of the approximate path vs "
           "the exact path (NaN before any measurement).")
    p.head("glint_fleet_index_recall_gate_ok", "gauge",
           "Per-replica recall-gate verdict (a failing gate holds "
           "that replica's exact path live).")
    for r in replicas:
        index = (r.get("snapshot") or {}).get("index") or {}
        if not index.get("enabled"):
            continue
        label = {"replica": r.get("url", "")}
        p.sample("glint_fleet_index_recall_at10", label,
                 index.get("recall_at10"))
        p.sample("glint_fleet_index_recall_gate_ok", label,
                 1 if index.get("recall_gate_ok") else 0)
    # Multi-process data plane (ISSUE 19): per-shard balancer blocks
    # (shard 0 = the supervisor's in-process balancer) + the retry
    # and QoS admission counters summed across shards in "balancer".
    p.head("glint_fleet_retry_after_honored_total", "counter",
           "All-replicas-shed retries that backed off by the replicas' "
           "own Retry-After hint before the next forward round.")
    p.sample("glint_fleet_retry_after_honored_total", None,
             bal.get("retry_after_honored_total", 0))
    dplane = doc.get("data_plane") or {}
    p.head("glint_fleet_balancer_procs", "gauge",
           "Balancer processes sharing the fleet listen port "
           "(SO_REUSEPORT or an inherited listener fd).")
    p.sample("glint_fleet_balancer_procs", None,
             dplane.get("balancer_procs", 1))
    shards = doc.get("balancer_shards") or []
    p.head("glint_fleet_shard_up", "gauge",
           "Whether the balancer shard answered the last control-"
           "channel snapshot.")
    for s in shards:
        p.sample("glint_fleet_shard_up",
                 {"shard": str(s.get("shard", ""))},
                 1 if s.get("up") else 0)
    for name, key, help_ in [
        ("glint_fleet_shard_proxied_total", "proxied_total",
         "Requests this balancer shard forwarded."),
        ("glint_fleet_shard_shed_retries_total", "shed_retries_total",
         "Shed-driven replica retries on this balancer shard."),
        ("glint_fleet_shard_exhausted_total", "exhausted_total",
         "Requests this shard relayed as all-replicas-shed."),
    ]:
        p.head(name, "counter", help_)
        for s in shards:
            stats = s.get("stats") or {}
            p.sample(name, {"shard": str(s.get("shard", ""))},
                     stats.get(key, 0))
    p.head("glint_fleet_shard_requests_total", "counter",
           "Device-path requests observed by the shard's forward path, "
           "by endpoint.")
    p.head("glint_fleet_shard_request_p95_ms", "gauge",
           "Forward-path p95 latency of the shard, by endpoint "
           "(client-observed: queueing + replica round trip).")
    for s in shards:
        serving = s.get("serving") or {}
        for ep, es in sorted((serving.get("endpoints") or {}).items()):
            lbl = {"shard": str(s.get("shard", "")), "endpoint": ep}
            p.sample("glint_fleet_shard_requests_total", lbl,
                     es.get("count", 0))
            p.sample("glint_fleet_shard_request_p95_ms", lbl,
                     es.get("p95_ms"))
    # Warm-spare autoscaler (ISSUE 19).
    auto = doc.get("autoscale") or {}
    if auto:
        p.head("glint_fleet_autoscale_live", "gauge",
               "Replicas currently live (serving traffic, no holds).")
        p.sample("glint_fleet_autoscale_live", None, auto.get("live", 0))
        p.head("glint_fleet_autoscale_spares", "gauge",
               "Warm spares parked by the autoscaler (launched and "
               "warmed, held out of rotation).")
        p.sample("glint_fleet_autoscale_spares", None,
                 auto.get("spares", 0))
        for name, key, help_ in [
            ("glint_fleet_autoscale_ups_total", "scale_ups_total",
             "Scale-up transitions (warm-spare readmits)."),
            ("glint_fleet_autoscale_downs_total", "scale_downs_total",
             "Scale-down transitions (live replicas parked to spare)."),
            ("glint_fleet_autoscale_pinned_skips_total",
             "pinned_skips_total",
             "Policy steps skipped because a rollout/canary pinned "
             "the replica set."),
            ("glint_fleet_autoscale_steps_total", "steps_total",
             "Autoscaler policy evaluations."),
        ]:
            p.head(name, "counter", help_)
            p.sample(name, None, auto.get(key, 0))
        p.head("glint_fleet_autoscale_last_shed_rate", "gauge",
               "Fleet shed rate (sheds/sec, QoS sheds included) at the "
               "last policy step.")
        p.sample("glint_fleet_autoscale_last_shed_rate", None,
                 auto.get("last_shed_rate", 0))
    # QoS admission (ISSUE 19): per-tenant quota + class shed
    # accounting from the balancer's front door.
    qos = bal.get("qos") or {}
    if qos:
        p.head("glint_fleet_qos_admitted_total", "counter",
               "Requests admitted by the QoS gate, by priority class.")
        for cls, n in sorted((qos.get("admitted_total") or {}).items()):
            p.sample("glint_fleet_qos_admitted_total",
                     {"class": cls}, n)
        p.head("glint_fleet_qos_shed_total", "counter",
               "Requests shed by the QoS gate, by reason (tenant "
               "quota, bulk-class inflight cap, infeasible deadline).")
        for reason, n in sorted((qos.get("shed_total") or {}).items()):
            p.sample("glint_fleet_qos_shed_total",
                     {"reason": reason}, n)
        p.head("glint_fleet_qos_tenant_shed_total", "counter",
               "Requests shed by the QoS gate, by tenant "
               "(X-Glint-Tenant).")
        for tenant, n in sorted(
                (qos.get("per_tenant_shed_total") or {}).items()):
            p.sample("glint_fleet_qos_tenant_shed_total",
                     {"tenant": tenant}, n)
        p.head("glint_fleet_qos_bulk_inflight", "gauge",
               "Bulk-class requests currently in flight.")
        p.sample("glint_fleet_qos_bulk_inflight", None,
                 qos.get("bulk_inflight", 0))
        p.head("glint_fleet_qos_bulk_inflight_peak", "gauge",
               "Peak concurrent bulk-class requests since boot.")
        p.sample("glint_fleet_qos_bulk_inflight_peak", None,
                 qos.get("bulk_inflight_peak", 0))
    return p.text()


# ----------------------------------------------------------------------
# Text-format lint
# ----------------------------------------------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"'
_VALUE = r"(?:NaN|[+-]?Inf|[+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)"
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(\{{{_LABEL}(?:,{_LABEL})*\}})?"
    rf" ({_VALUE})"
    # Optional OpenMetrics-style exemplar: " # {labels} value".
    rf"( # \{{{_LABEL}(?:,{_LABEL})*\}} {_VALUE})?$"
)
_COMMENT_RE = re.compile(rf"^# (HELP|TYPE) ({_NAME})( .*)?$")
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def lint_prometheus_text(text: str) -> None:
    """Validate the subset of the 0.0.4 text format the renderers emit.

    Raises ``ValueError`` naming the first offending line; returns None
    on clean input. Checks: trailing newline, HELP/TYPE comment grammar,
    valid metric types, no duplicate TYPE, TYPE declared before its
    samples, and full sample-line grammar (metric/label name charset,
    escaped label values, parseable value).
    """
    if not text.endswith("\n"):
        raise ValueError("exposition must end with a newline")
    typed: dict = {}
    sampled: set = set()
    for i, line in enumerate(text.split("\n")[:-1], 1):
        if line == "":
            continue
        if line.startswith("#"):
            m = _COMMENT_RE.match(line)
            if not m:
                raise ValueError(f"line {i}: malformed comment: {line!r}")
            if m.group(1) == "TYPE":
                name, t = m.group(2), (m.group(3) or "").strip()
                if t not in _TYPES:
                    raise ValueError(
                        f"line {i}: invalid metric type {t!r} for {name}"
                    )
                if name in typed:
                    raise ValueError(f"line {i}: duplicate TYPE for {name}")
                if name in sampled:
                    raise ValueError(
                        f"line {i}: TYPE for {name} after its samples"
                    )
                typed[name] = t
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {i}: malformed sample line: {line!r}")
        base = re.sub(r"_(bucket|sum|count)$", "", m.group(1))
        sampled.add(m.group(1))
        sampled.add(base)
