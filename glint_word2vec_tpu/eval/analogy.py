"""Embedding-quality evaluation: analogy accuracy and synonym gates.

The reference's quality bar lives only inside its integration tests — two
hard-coded checks on a fixed corpus: ``wien`` must appear in the top-10
synonyms of ``österreich`` with cosine > 0.9
(ServerSideGlintWord2VecSpec.scala:297-302) and ``berlin`` in the top-10 of
``wien - österreich + deutschland`` (Spec.scala:342-348), with the analogy
arithmetic done caller-side (Spec.scala:342-344). This module promotes that
bar to a first-class subsystem: batched a:b :: c:? accuracy over standard
question files (the Google analogy-set format word2vec ships), arbitrary
synonym gates, and device-side scoring — each query's answer comes from the
engine's distributed top-k, never a host-side O(vocab) scan
(SURVEY.md §3.3 hot-loop note).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclass
class AnalogyResult:
    """Accuracy of one evaluation run, per section and overall."""

    total: int = 0
    correct: int = 0
    skipped: int = 0  # questions with any OOV word
    sections: Dict[str, Tuple[int, int]] = field(default_factory=dict)

    @property
    def accuracy(self) -> float:
        return self.correct / self.total if self.total else 0.0

    def to_dict(self) -> dict:
        return {
            "total": self.total,
            "correct": self.correct,
            "skipped_oov": self.skipped,
            "accuracy": round(self.accuracy, 4),
            "sections": {
                k: {"correct": c, "total": t, "accuracy": round(c / t, 4) if t else 0.0}
                for k, (c, t) in self.sections.items()
            },
        }


def parse_analogy_file(path: str, lowercase: bool = True):
    """Parse the standard analogy question-file format: ``: section`` header
    lines followed by ``a b c d`` rows (d is the expected answer to
    a:b :: c:?)."""
    sections: List[Tuple[str, List[Tuple[str, str, str, str]]]] = []
    current: List[Tuple[str, str, str, str]] = []
    name = "default"
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line.startswith(":"):
                if current:
                    sections.append((name, current))
                name = line[1:].strip() or "default"
                current = []
                continue
            parts = line.lower().split() if lowercase else line.split()
            if len(parts) == 4:
                current.append(tuple(parts))
    if current:
        sections.append((name, current))
    return sections


def evaluate_analogies(
    model,
    questions,
    top_k: int = 1,
    batch_size: int = 1024,
) -> AnalogyResult:
    """Accuracy on a:b :: c:? questions (``questions`` as returned by
    :func:`parse_analogy_file`, or a flat list of 4-tuples).

    A question counts as correct when the expected word appears in the
    ``top_k`` nearest neighbors of ``b - a + c`` (query words excluded,
    word2vec convention). Queries are scored in device batches: the query
    matrix goes through one distributed matvec batch per chunk via the
    engine, so evaluation scales with vocab exactly like findSynonyms.
    OOV questions are skipped and counted (gensim/word2vec convention).
    """
    questions = list(questions)
    if questions and len(questions[0]) == 4 and all(
        isinstance(x, str) for x in questions[0]
    ):
        flat = [("default", questions)]  # flat list of (a, b, c, d)
    else:
        flat = [(name, list(qs)) for name, qs in questions]

    res = AnalogyResult()
    vocab = model.vocab
    for name, qs in flat:
        sec_correct = sec_total = 0
        # Resolve words; skip OOV questions.
        resolved = []
        for a, b, c, d in qs:
            ia, ib = vocab.word_index.get(a), vocab.word_index.get(b)
            ic, id_ = vocab.word_index.get(c), vocab.word_index.get(d)
            if None in (ia, ib, ic, id_):
                res.skipped += 1
                continue
            resolved.append((a, b, c, d))
        for s in range(0, len(resolved), batch_size):
            chunk = resolved[s : s + batch_size]
            # One vector fetch for all of a, b, c across the chunk, then ONE
            # batched distributed top-k dispatch; chunks are zero-padded to
            # batch_size so the device sees a single compiled query shape.
            abc = model.transform_words(
                [q[0] for q in chunk]
                + [q[1] for q in chunk]
                + [q[2] for q in chunk]
            )
            n = len(chunk)
            A, B, C = abc[:n], abc[n : 2 * n], abc[2 * n :]
            queries = B - A + C
            if len(chunk) < batch_size:
                queries = np.pad(
                    queries, ((0, batch_size - len(chunk)), (0, 0))
                )
            hits = model.find_synonyms_batch(queries, top_k + 3)
            for i, (a, b, c, d) in enumerate(chunk):
                exclude = {a, b, c}
                answers = [
                    w for w, _ in hits[i] if w not in exclude
                ][:top_k]
                sec_correct += int(d in answers)
                sec_total += 1
        prev_c, prev_t = res.sections.get(name, (0, 0))
        res.sections[name] = (prev_c + sec_correct, prev_t + sec_total)
        res.correct += sec_correct
        res.total += sec_total
    return res


def evaluate_synonym_gate(
    model,
    word: str,
    expected: str,
    top: int = 10,
    min_similarity: Optional[float] = None,
) -> Tuple[bool, Optional[float]]:
    """The reference's synonym quality gate as a reusable check: does
    ``expected`` appear in the ``top`` synonyms of ``word`` (optionally with
    cosine >= ``min_similarity``)? Returns (passed, similarity-or-None)."""
    for w, s in model.find_synonyms(word, top):
        if w == expected:
            if min_similarity is not None and s < min_similarity:
                return False, s
            return True, s
    return False, None
