from glint_word2vec_tpu.eval.analogy import (
    AnalogyResult,
    evaluate_analogies,
    evaluate_synonym_gate,
    parse_analogy_file,
)

__all__ = [
    "AnalogyResult",
    "evaluate_analogies",
    "evaluate_synonym_gate",
    "parse_analogy_file",
]
