"""Reference-surface compatibility layer: the PySpark binding API
(`ServerSideGlintWord2Vec` / `ServerSideGlintWord2VecModel`,
src/main/python/ml_glintword2vec.py:38-385) re-exposed over the TPU
framework, so code written against the reference's estimator/model surface
ports by changing an import.

Parameter mapping (camelCase as in ml_glintword2vec.py:101-170):

  vectorSize / windowSize / stepSize / batchSize / n / minCount / maxIter /
  maxSentenceLength / seed / subsampleRatio / unigramTableSize
      -> the same-meaning Word2VecParams fields.
  numPartitions        -> data-parallel mesh axis (clamped to devices).
  numParameterServers  -> model-parallel mesh axis (each shard owns 1/n of
      the matrices — the direct analogue of README.md:69), clamped to the
      available device count the way the reference adapts the server count
      to its cluster.
  parameterServerHost / parameterServerConfig -> no analogue: there is no
      server process to connect to (the "cluster" is the device mesh in
      this process). Accepted for signature compatibility; a non-empty
      host raises.
  inputCol / outputCol -> stored for API compatibility; this layer takes
      tokenized sentences directly instead of DataFrames.

Documented behavioral divergences (see README "Faithfulness"):
  * subsampleRatio defaults to 0.0 here. The reference declares 1e-6 but
    its integer-division bug (mllib:375) makes subsampling a silent no-op,
    so 0.0 IS the reference's de-facto behavior; passing a ratio here opts
    into the *fixed* float semantics.
  * unigramTableSize defaults to None here (exact alias sampling of the
    same unigram^0.75 distribution) instead of the reference's quantized
    1e8-entry table; pass a size to opt into the quantized compatibility
    mode.
  * `stop(terminateOtherClients=True)` is accepted but meaningless: no
    other clients exist.

``seed=None`` draws a fresh random seed per fit, matching the reference.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from glint_word2vec_tpu.models.word2vec import Word2Vec, Word2VecModel
from glint_word2vec_tpu.utils.params import Word2VecParams


def _mesh_axes(numPartitions: int, numParameterServers: int):
    """Clamp the requested (workers, servers) topology to the devices
    actually present — the reference similarly derives its server count
    from the live cluster (Client.getNumExecutors, mllib:356)."""
    import jax

    n_dev = len(jax.devices())
    num_model = max(1, min(numParameterServers, n_dev))
    num_data = max(1, min(numPartitions, n_dev // num_model))
    if (numParameterServers, numPartitions) != (num_model, num_data):
        warnings.warn(
            f"requested topology {numPartitions}x{numParameterServers} "
            f"(partitions x parameter servers) clamped to mesh "
            f"{num_data}x{num_model} for {n_dev} device(s)"
        )
    return num_data, num_model


class ServerSideGlintWord2Vec:
    """Estimator with the reference's parameter surface
    (ml_glintword2vec.py:138-170 defaults, except subsampleRatio — see
    module docstring)."""

    def __init__(
        self,
        vectorSize: int = 100,
        minCount: int = 5,
        numPartitions: int = 1,
        stepSize: float = 0.01875,
        maxIter: int = 1,
        seed: Optional[int] = None,
        inputCol: Optional[str] = None,
        outputCol: Optional[str] = None,
        windowSize: int = 5,
        maxSentenceLength: int = 1000,
        batchSize: int = 50,
        n: int = 5,
        subsampleRatio: float = 0.0,
        numParameterServers: int = 5,
        parameterServerHost: str = "",
        unigramTableSize: Optional[int] = None,
    ):
        self._kw = {}
        self.setParams(
            vectorSize=vectorSize, minCount=minCount,
            numPartitions=numPartitions, stepSize=stepSize, maxIter=maxIter,
            seed=seed, inputCol=inputCol, outputCol=outputCol,
            windowSize=windowSize, maxSentenceLength=maxSentenceLength,
            batchSize=batchSize, n=n, subsampleRatio=subsampleRatio,
            numParameterServers=numParameterServers,
            parameterServerHost=parameterServerHost,
            unigramTableSize=unigramTableSize,
        )

    # -- params ---------------------------------------------------------

    #: The full parameter surface (ml_glintword2vec.py:138-170).
    _PARAM_NAMES = frozenset(
        {
            "vectorSize", "minCount", "numPartitions", "stepSize", "maxIter",
            "seed", "inputCol", "outputCol", "windowSize",
            "maxSentenceLength", "batchSize", "n", "subsampleRatio",
            "numParameterServers", "parameterServerHost", "unigramTableSize",
        }
    )

    def setParams(self, **kwargs) -> "ServerSideGlintWord2Vec":
        unknown = set(kwargs) - self._PARAM_NAMES
        if unknown:
            # Same contract as the keyword_only PySpark surface: a typoed
            # or wrong-dialect name must fail loudly, not silently train
            # with defaults.
            raise TypeError(
                f"unknown param(s) {sorted(unknown)}; valid params: "
                f"{sorted(self._PARAM_NAMES)}"
            )
        self._kw.update(kwargs)
        return self

    def _get(self, name):
        return self._kw[name]

    # Per-param setters/getters, mirroring ml_glintword2vec.py:172-302.
    def setVectorSize(self, value):
        return self.setParams(vectorSize=value)

    def getVectorSize(self):
        return self._get("vectorSize")

    def setMinCount(self, value):
        return self.setParams(minCount=value)

    def getMinCount(self):
        return self._get("minCount")

    def setNumPartitions(self, value):
        return self.setParams(numPartitions=value)

    def getNumPartitions(self):
        return self._get("numPartitions")

    def setStepSize(self, value):
        return self.setParams(stepSize=value)

    def getStepSize(self):
        return self._get("stepSize")

    def setMaxIter(self, value):
        return self.setParams(maxIter=value)

    def getMaxIter(self):
        return self._get("maxIter")

    def setSeed(self, value):
        return self.setParams(seed=value)

    def getSeed(self):
        return self._get("seed")

    def setInputCol(self, value):
        return self.setParams(inputCol=value)

    def getInputCol(self):
        return self._get("inputCol")

    def setOutputCol(self, value):
        return self.setParams(outputCol=value)

    def getOutputCol(self):
        return self._get("outputCol")

    def setWindowSize(self, value):
        return self.setParams(windowSize=value)

    def getWindowSize(self):
        return self._get("windowSize")

    def setMaxSentenceLength(self, value):
        return self.setParams(maxSentenceLength=value)

    def getMaxSentenceLength(self):
        return self._get("maxSentenceLength")

    def setBatchSize(self, value):
        return self.setParams(batchSize=value)

    def getBatchSize(self):
        return self._get("batchSize")

    def setN(self, value):
        return self.setParams(n=value)

    def getN(self):
        return self._get("n")

    def setSubsampleRatio(self, value):
        return self.setParams(subsampleRatio=value)

    def getSubsampleRatio(self):
        return self._get("subsampleRatio")

    def setNumParameterServers(self, value):
        return self.setParams(numParameterServers=value)

    def getNumParameterServers(self):
        return self._get("numParameterServers")

    def setParameterServerHost(self, value):
        return self.setParams(parameterServerHost=value)

    def getParameterServerHost(self):
        return self._get("parameterServerHost")

    def setUnigramTableSize(self, value):
        return self.setParams(unigramTableSize=value)

    def getUnigramTableSize(self):
        return self._get("unigramTableSize")

    # -- fit ------------------------------------------------------------

    def fit(
        self, sentences: Sequence[Sequence[str]]
    ) -> "ServerSideGlintWord2VecModel":
        """Train. Takes tokenized sentences (the content of the reference's
        input DataFrame column, ml:286) and returns the fitted model."""
        kw = self._kw
        if kw.get("parameterServerHost"):
            raise ValueError(
                "parameterServerHost has no analogue: there is no separate "
                "parameter-server cluster to connect to (the device mesh "
                "lives in this process); leave it empty"
            )
        num_data, num_model = _mesh_axes(
            kw["numPartitions"], kw["numParameterServers"]
        )
        # The reference's batchSize is per-worker and unconstrained by
        # numPartitions (e.g. the defaults batchSize=50, numPartitions=4);
        # here the global batch must divide the data axis. Round up instead
        # of failing a valid reference configuration.
        batch_size = kw["batchSize"]
        if batch_size % num_data:
            rounded = -(-batch_size // num_data) * num_data
            warnings.warn(
                f"batchSize={batch_size} is not divisible by the data-axis "
                f"size {num_data}; rounding up to {rounded}",
                stacklevel=2,
            )
            batch_size = rounded
        params = Word2VecParams(
            vector_size=kw["vectorSize"],
            window=kw["windowSize"],
            step_size=kw["stepSize"],
            batch_size=batch_size,
            num_negatives=kw["n"],
            subsample_ratio=kw["subsampleRatio"],
            min_count=kw["minCount"],
            num_iterations=kw["maxIter"],
            max_sentence_length=kw["maxSentenceLength"],
            # seed=None means a fresh random seed, as in the reference.
            seed=(
                kw["seed"]
                if kw["seed"] is not None
                else int(np.random.default_rng().integers(2**31 - 1))
            ),
            num_partitions=num_data,
            num_shards=num_model,
            unigram_table_size=kw["unigramTableSize"],
        )
        model = Word2Vec(params).fit(list(sentences))
        return ServerSideGlintWord2VecModel(model)


class ServerSideGlintWord2VecModel:
    """Model with the reference surface (ml_glintword2vec.py:311-383)."""

    def __init__(self, model: Word2VecModel):
        self._model = model

    def getVectors(self) -> List[Tuple[str, np.ndarray]]:
        """All (word, vector) rows — the reference's getVectors DataFrame
        (ml:342-364) as a list. Streams from the device; the 8 GB broadcast
        caveat in the reference docstring does not apply."""
        return list(self._model.get_vectors())

    def findSynonyms(
        self, word: Union[str, np.ndarray, Sequence[float]], num: int
    ) -> List[Tuple[str, float]]:
        """Top-``num`` (word, cosine) pairs; ``word`` may be a string or a
        vector, as in the reference (ml_glintword2vec.py:330-339)."""
        if isinstance(word, str):
            return self._model.find_synonyms(word, num)
        return self._model.find_synonyms_vector(
            np.asarray(word, np.float32), num
        )

    def findSynonymsArray(self, word, num) -> List[Tuple[str, float]]:
        """Alias of :meth:`findSynonyms` (the reference splits DataFrame and
        array flavors, ml_glintword2vec.py:341-351; here both are lists)."""
        return self.findSynonyms(word, num)

    def transform(
        self, sentences: Sequence[Sequence[str]]
    ) -> np.ndarray:
        """Sentence embeddings by device-side averaging — the DataFrame
        transform path (ml:443-459): OOV dropped, empty rows zero."""
        return self._model.transform_sentences(sentences)

    def save(self, path: str) -> None:
        """Save, refusing to clobber an existing model — the MLWritable
        ErrorIfExists default; use ``write().overwrite().save(path)`` to
        replace (ml:471,504-507 delegation chain)."""
        self.write().save(path)

    def write(self):  # minimal MLWritable-style shim
        class _Writer:
            def __init__(self, m):
                self._m = m
                self._overwrite = False

            def save(self, path):
                import os

                if not self._overwrite and os.path.exists(path):
                    raise FileExistsError(
                        f"model path {path} already exists; call "
                        f".write().overwrite().save(path) to replace it"
                    )
                self._m.save(path)

            def overwrite(self):
                self._overwrite = True
                return self

        return _Writer(self._model)

    @classmethod
    def load(
        cls, path: str, parameterServerHost: str = ""
    ) -> "ServerSideGlintWord2VecModel":
        """Load a saved model. ``parameterServerHost`` mirrors the
        reference's load-time re-homing override (ml_glintword2vec.py:
        353-373); here re-homing means choosing a mesh, so the host string
        must stay empty — pass a mesh to Word2VecModel.load for custom
        topologies."""
        if parameterServerHost:
            raise ValueError(
                "parameterServerHost has no analogue; load onto a custom "
                "topology with Word2VecModel.load(path, mesh=...)"
            )
        # Word2VecModel.load clamps the saved topology to the live device
        # count itself (the re-homing capability, ml:584-586).
        return cls(Word2VecModel.load(path))

    def stop(self, terminateOtherClients: bool = False) -> None:
        """Release the distributed matrices (ml_glintword2vec.py:375-383).
        ``terminateOtherClients`` is accepted for signature parity; there
        are no other clients in-process."""
        del terminateOtherClients
        self._model.stop()
