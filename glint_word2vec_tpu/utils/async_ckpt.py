"""Background checkpoint writer: the host half of non-blocking saves.

The fit loops' checkpoint sites used to serialize the device behind host
work: ``engine.save`` blocked the dispatching thread for the full
device->host copy + ``np.save`` + fsync + rename of both tables, so
every checkpoint was a bubble in the device pipeline (ISSUE 5). The
engine now snapshots the tables device->host on the calling thread —
parallel per-block deep copies, the ONLY blocking work it still does —
and hands serialization + durability fsyncs + atomic commit to the
single writer thread this module owns. The residual call-site pause is
the snapshot copy alone (``bench.py stall_overlap`` measures it at
<20% of the blocking save's pause).

Deliberately a depth-1 pipeline: at most ONE snapshot is ever in flight.
A second request blocks until the first commits (counted in
``blocked_waits`` — visible on the heartbeat as back-pressure), which
bounds the transient snapshot memory to one table pair and keeps commits
strictly ordered, so ``train_state.json`` can never flip to a checkpoint
older than one already committed.

Failure contract: a failed write never crashes the training thread
mid-dispatch — the error is held and re-raised at the next ``submit`` or
at the ``wait()`` barrier the fit loops run before declaring the run
done. Because the commit callback (the ``train_state.json`` flip) runs
only after a successful write, a failed or killed write leaves the
previous committed checkpoint authoritative.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class SnapshotWriterHung(RuntimeError):
    """A wait on the background checkpoint writer exceeded its timeout:
    the writer thread is stuck (dead filesystem, hung fsync, injected
    fault). The previous committed checkpoint is still authoritative —
    the in-flight snapshot never renamed."""


class AsyncSnapshotWriter:
    """Single daemon writer thread with a one-deep job hand-off.

    Written for a single submitting thread (the fit loop); concurrent
    submitters are not supported (they would race the in-flight guard).
    The writer thread is started lazily on first submit and is a daemon,
    so an abandoned engine never pins process exit.
    """

    def __init__(self, name: str = "glint-ckpt-writer"):
        self._jobs: queue.Queue = queue.Queue(maxsize=1)
        self._mu = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._name = name
        #: Snapshots queued but not yet committed (0 or 1).
        self.pending = 0
        #: Times a submit found the previous snapshot still in flight and
        #: had to block for it — checkpoint back-pressure, surfaced on
        #: the heartbeat (``async_save_waits``).
        self.blocked_waits = 0
        #: Successfully committed snapshots.
        self.commits = 0
        #: Wall seconds of the most recent write job (host-side copy +
        #: serialization + commit), successful or not.
        self.last_write_seconds: Optional[float] = None
        #: time.time() of the most recent successful commit.
        self.last_commit_time: Optional[float] = None
        #: Human-readable label of the job currently in flight (the
        #: checkpoint path) — named in the hung-writer error so the
        #: operator knows which snapshot to inspect.
        self.current_job: Optional[str] = None

    # -- writer thread --------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=self._name
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            job = self._jobs.get()
            t0 = time.time()
            try:
                job()
                with self._mu:
                    self.commits += 1
                    self.last_commit_time = time.time()
            except BaseException as e:  # held for the submitting thread
                logger.error("async checkpoint write failed: %s", e)
                with self._mu:
                    self._error = e
            finally:
                with self._mu:
                    self.pending -= 1
                    self.last_write_seconds = time.time() - t0
                    self.current_job = None
                self._idle.set()

    # -- submitting-thread API ------------------------------------------

    def _await_idle(self, timeout: Optional[float]) -> None:
        """Wait for the writer to go idle; on timeout, log the stuck job
        and raise :class:`SnapshotWriterHung` instead of pinning the
        caller (the fit-exit barrier) forever."""
        if self._idle.wait(timeout):
            return
        with self._mu:
            job = self.current_job
        logger.error(
            "checkpoint writer hung: job %r still in flight after "
            "%.1fs; previous committed checkpoint remains authoritative",
            job, timeout,
        )
        raise SnapshotWriterHung(
            f"checkpoint writer did not finish {job!r} within "
            f"{timeout:.1f}s"
        )

    def wait_for_slot(self, timeout: Optional[float] = None) -> None:
        """Block until no snapshot is in flight (counted in
        ``blocked_waits`` when it actually blocks) and surface any prior
        write error. Callers invoke this BEFORE materializing a new
        snapshot, so transient snapshot memory stays bounded to ONE
        table pair — snapshotting first and blocking in submit would
        briefly hold two. ``timeout`` raises
        :class:`SnapshotWriterHung` instead of waiting forever."""
        if not self._idle.is_set():
            with self._mu:
                self.blocked_waits += 1
            self._await_idle(timeout)
        self.raise_pending_error()

    def submit(self, job: Callable[[], None],
               label: Optional[str] = None,
               timeout: Optional[float] = None) -> None:
        """Queue one snapshot job. Blocks while a previous snapshot is
        still in flight (the at-most-one guard; prefer
        :meth:`wait_for_slot` before building the snapshot); re-raises
        any error a previous job recorded — the failed save's state flip
        never ran, so the caller learns before trusting the checkpoint
        chain. ``label`` names the job in hung-writer diagnostics."""
        self._ensure_thread()
        self.wait_for_slot(timeout)
        with self._mu:
            self.pending += 1
            self.current_job = label or getattr(job, "__name__", "job")
        self._idle.clear()
        self._jobs.put(job)

    def wait(self, *, reraise: bool = True,
             timeout: Optional[float] = None) -> None:
        """Barrier: return once no snapshot is in flight. ``reraise``
        surfaces a held write error (the fit-exit barrier wants it; the
        exception-path cleanup barrier must not mask the original
        failure and passes False). With ``timeout``, a writer thread
        stuck past it logs the pending job and raises
        :class:`SnapshotWriterHung` instead of hanging fit exit forever
        (with ``reraise=False`` the hang is logged but NOT raised — the
        cleanup barrier must not mask the original failure it is
        unwinding)."""
        try:
            self._await_idle(timeout)
        except SnapshotWriterHung:
            if reraise:
                raise
            return
        if reraise:
            self.raise_pending_error()

    def raise_pending_error(self) -> None:
        with self._mu:
            e, self._error = self._error, None
        if e is not None:
            raise RuntimeError(
                "asynchronous checkpoint write failed; the previous "
                "committed checkpoint is still authoritative"
            ) from e

    def stats(self) -> dict:
        with self._mu:
            return {
                "pending": self.pending,
                "blocked_waits": self.blocked_waits,
                "commits": self.commits,
                "last_write_seconds": self.last_write_seconds,
                "last_commit_time": self.last_commit_time,
            }
