"""Training observability: throughput counters, step timing, profiler hook.

The reference's only training telemetry is a log line every 10k words with
the annealed alpha and the last positive dot product as a divergence canary
(mllib:399-413; SURVEY.md §5 "tracing: none"). This module is the richer
TPU-native replacement: words/sec and steps/sec over a sliding window,
wall-clock split between host batching and device step dispatch, a running
loss, and an optional ``jax.profiler`` trace capture around a step range.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from dataclasses import dataclass, field
from typing import List, Optional

logger = logging.getLogger(__name__)


@dataclass
class TrainingMetrics:
    """Accumulates per-run training statistics; cheap enough for every step."""

    log_every: int = 200
    #: Global words_done at construction (nonzero after a checkpoint
    #: resume); rates count only words processed by this invocation.
    base_words: int = 0
    steps: int = 0
    words_done: int = 0
    host_time: float = 0.0  # seconds spent producing batches
    step_time: float = 0.0  # seconds spent in train-step dispatch
    last_loss: Optional[float] = None
    #: Most recent per-step loss as an UNSYNCED device array; float()ed
    #: only at log points and in summary().
    _last_loss_lazy: Optional[object] = None
    _t_start: float = field(default_factory=time.time)
    _t_window: float = field(default_factory=time.time)
    _words_window: int = -1  # sentinel: initialized on first record_step
    history: List[dict] = field(default_factory=list)

    def __post_init__(self) -> None:
        self.words_done = self.base_words
        self._words_window = self.base_words

    def record_step(self, words_done: int, loss=None, alpha=None) -> None:
        self.steps += 1
        self.words_done = words_done
        if loss is not None:
            # Keep the device array without forcing a sync: float() blocks
            # the dispatch pipeline, so it happens only at log points and
            # in summary() — never per step.
            self._last_loss_lazy = loss
        if self.steps % self.log_every == 0:
            now = time.time()
            wps = (words_done - self._words_window) / max(now - self._t_window, 1e-9)
            if loss is not None:
                self.last_loss = float(loss)  # device sync point, on purpose
            entry = {
                "step": self.steps,
                "words_done": words_done,
                "words_per_sec": round(wps, 1),
                "alpha": alpha,
                "loss": self.last_loss,
                "host_frac": round(
                    self.host_time / max(self.host_time + self.step_time, 1e-9), 3
                ),
            }
            self.history.append(entry)
            logger.info(
                "step %d: %.0f words/s alpha=%s loss=%s host_frac=%s",
                self.steps, wps, alpha, self.last_loss, entry["host_frac"],
            )
            self._t_window, self._words_window = now, words_done

    @contextlib.contextmanager
    def timing(self, kind: str):
        t0 = time.time()
        try:
            yield
        finally:
            dt = time.time() - t0
            if kind == "host":
                self.host_time += dt
            else:
                self.step_time += dt

    def summary(self) -> dict:
        wall = max(time.time() - self._t_start, 1e-9)
        if self._last_loss_lazy is not None:
            # One sync at summary time so short runs (fewer than log_every
            # steps) still report a final loss. An async dispatch failure
            # surfaces here, not at the step — keep the last synced loss
            # rather than crashing the summary; either way drop the device
            # buffer so it is not pinned for the run's lifetime.
            try:
                self.last_loss = float(self._last_loss_lazy)
            except Exception:
                pass
            self._last_loss_lazy = None
        return {
            "steps": self.steps,
            "words_done": self.words_done,
            "wall_seconds": round(wall, 2),
            "words_per_sec": round((self.words_done - self.base_words) / wall, 1),
            "host_time": round(self.host_time, 2),
            "step_time": round(self.step_time, 2),
            "final_loss": self.last_loss,
        }

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"summary": self.summary(), "history": self.history}, f)


@contextlib.contextmanager
def profile_trace(out_dir: Optional[str]):
    """Capture a ``jax.profiler`` trace into ``out_dir`` (None = no-op).

    View with TensorBoard's profile plugin or xprof; the TPU-native answer
    to the reference having no profiling at all (SURVEY.md §5).
    """
    if not out_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(out_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
