"""Training observability: throughput counters, step timing, profiler hook.

The reference's only training telemetry is a log line every 10k words with
the annealed alpha and the last positive dot product as a divergence canary
(mllib:399-413; SURVEY.md §5 "tracing: none"). This module is the richer
TPU-native replacement: words/sec and steps/sec over a sliding window,
wall-clock split between host batching and device step dispatch, a running
loss, and an optional ``jax.profiler`` trace capture around a step range.
"""

from __future__ import annotations

import bisect
import contextlib
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class TrainingMetrics:
    """Accumulates per-run training statistics; cheap enough for every step."""

    log_every: int = 200
    #: Global words_done at construction (nonzero after a checkpoint
    #: resume); rates count only words processed by this invocation.
    base_words: int = 0
    steps: int = 0
    words_done: int = 0
    host_time: float = 0.0  # seconds spent producing batches
    step_time: float = 0.0  # seconds spent in train-step dispatch
    #: Host-side seconds during which the dispatch pipeline was starved:
    #: blocking checkpoint saves, waits for the batch producer, and
    #: epoch-boundary compaction syncs. An upper-bound PROXY for device
    #: idle time (the host may block on work the device is still busy
    #: with), but its direction is exact: async checkpointing, deferred
    #: readbacks, and prefetch overlap each shrink it (ISSUE 5).
    stall_time: float = 0.0
    last_loss: Optional[float] = None
    #: Most recent per-step loss as an UNSYNCED device array; float()ed
    #: only at log points and in summary().
    _last_loss_lazy: Optional[object] = None
    _t_start: float = field(default_factory=time.time)
    _t_window: float = field(default_factory=time.time)
    _words_window: int = -1  # sentinel: initialized on first record_step
    history: List[dict] = field(default_factory=list)
    #: Bound on retained history entries: one dict lands every
    #: ``log_every`` steps, and unbounded it leaks host memory into the
    #: final dump() on long production runs. Oldest entries drop first;
    #: ``history_dropped`` counts them so the dump is honest about gaps.
    history_max: int = 4096
    history_dropped: int = 0

    def __post_init__(self) -> None:
        self.words_done = self.base_words
        self._words_window = self.base_words
        self.history = deque(self.history, maxlen=max(1, self.history_max))

    def record_step(self, words_done: int, loss=None, alpha=None) -> None:
        self.steps += 1
        self.words_done = words_done
        if loss is not None:
            # Keep the device array without forcing a sync: float() blocks
            # the dispatch pipeline, so it happens only at log points and
            # in summary() — never per step.
            self._last_loss_lazy = loss
        if self.steps % self.log_every == 0:
            now = time.time()
            wps = (words_done - self._words_window) / max(now - self._t_window, 1e-9)
            if loss is not None:
                # graftlint: ignore[sync-point] deliberate log-cadence sync: once per log_every groups, never per step
                self.last_loss = float(loss)
            entry = {
                "step": self.steps,
                "words_done": words_done,
                "words_per_sec": round(wps, 1),
                "alpha": alpha,
                "loss": self.last_loss,
                "host_frac": round(
                    self.host_time / max(self.host_time + self.step_time, 1e-9), 3
                ),
            }
            if len(self.history) == self.history.maxlen:
                self.history_dropped += 1
            self.history.append(entry)
            logger.info(
                "step %d: %.0f words/s alpha=%s loss=%s host_frac=%s",
                self.steps, wps, alpha, self.last_loss, entry["host_frac"],
            )
            self._t_window, self._words_window = now, words_done

    @contextlib.contextmanager
    def timing(self, kind: str):
        t0 = time.time()
        try:
            yield
        finally:
            dt = time.time() - t0
            if kind == "host":
                self.host_time += dt
            else:
                self.step_time += dt

    def record_stall(self, seconds: float) -> None:
        self.stall_time += seconds

    @contextlib.contextmanager
    def stall_timing(self):
        """Charge the wrapped block to ``stall_time`` (composable with
        :meth:`timing`; the buckets are independent)."""
        t0 = time.time()
        try:
            yield
        finally:
            self.record_stall(time.time() - t0)

    def summary(self) -> dict:
        wall = max(time.time() - self._t_start, 1e-9)
        if self._last_loss_lazy is not None:
            # One sync at summary time so short runs (fewer than log_every
            # steps) still report a final loss. An async dispatch failure
            # surfaces here, not at the step — keep the last synced loss
            # rather than crashing the summary; either way drop the device
            # buffer so it is not pinned for the run's lifetime.
            try:
                # graftlint: ignore[sync-point] the one end-of-fit lazy-loss sync; the device is idle by the time summary() runs
                self.last_loss = float(self._last_loss_lazy)
            except Exception as e:
                # Keep the last synced loss, but never silently: a stale
                # last_loss with no trace hid real dispatch failures
                # (ADVICE.md round 5).
                logger.warning(
                    "final lazy-loss sync failed (last_loss=%s may be "
                    "stale): %s", self.last_loss, e,
                )
            self._last_loss_lazy = None
        return {
            "steps": self.steps,
            "words_done": self.words_done,
            "wall_seconds": round(wall, 2),
            "words_per_sec": round((self.words_done - self.base_words) / wall, 1),
            "host_time": round(self.host_time, 2),
            "step_time": round(self.step_time, 2),
            "device_stall_seconds": round(self.stall_time, 3),
            "final_loss": self.last_loss,
        }

    def dump(self, path: str) -> None:
        """Atomic (temp + ``os.replace``): a crash mid-write can never
        leave a truncated JSON that poisons downstream tooling."""
        from glint_word2vec_tpu.utils import atomic_write_json

        atomic_write_json(path, {
            "summary": self.summary(),
            "history": list(self.history),
            "history_dropped": self.history_dropped,
        })


class LatencyHistogram:
    """Fixed log-spaced latency histogram: O(1) memory per endpoint,
    quantiles by linear interpolation inside the winning bucket.

    Bucket edges run 50µs .. ~20min with a sqrt(2) growth factor, so
    every quantile estimate is within ~±20% of the true value — plenty
    for the p50/p95/p99 serving dashboards this feeds (the reference has
    no serving telemetry at all, SURVEY.md §5)."""

    _EDGES = [5e-5 * (2 ** (i / 2.0)) for i in range(64)]

    __slots__ = ("counts", "n", "total", "max")

    def __init__(self) -> None:
        self.counts = [0] * (len(self._EDGES) + 1)
        self.n = 0
        self.total = 0.0
        self.max = 0.0

    def record(self, seconds: float) -> None:
        self.counts[bisect.bisect_right(self._EDGES, seconds)] += 1
        self.n += 1
        self.total += seconds
        if seconds > self.max:
            self.max = seconds

    def quantile(self, q: float) -> float:
        if self.n == 0:
            return 0.0
        target = q * self.n
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if c and acc >= target:
                lo = self._EDGES[i - 1] if i > 0 else 0.0
                hi = self._EDGES[i] if i < len(self._EDGES) else self.max
                hi = min(max(hi, lo), self.max) if self.max else hi
                return lo + (hi - lo) * ((target - (acc - c)) / c)
        return self.max

    # -- cross-process state / merging (ISSUE 8) -----------------------
    # The gang aggregator merges per-rank histograms into one fleet
    # histogram: bucket counts are position-aligned (every instance
    # shares _EDGES), so merging is exact — the merged quantiles are
    # what one histogram fed the whole population would report.

    def state(self) -> dict:
        """JSON-serializable snapshot of the histogram (sparse bucket
        counts), for crossing a process boundary (heartbeat status
        files, serving /metrics) into :meth:`from_state`/:meth:`merge`."""
        return {
            "counts": {
                str(i): c for i, c in enumerate(self.counts) if c
            },
            "n": self.n,
            "total": self.total,
            "max": self.max,
        }

    @classmethod
    def from_state(cls, state: dict) -> "LatencyHistogram":
        h = cls()
        for i, c in (state.get("counts") or {}).items():
            i = int(i)
            if 0 <= i < len(h.counts):
                h.counts[i] += int(c)
        h.n = int(state.get("n", 0))
        h.total = float(state.get("total", 0.0))
        h.max = float(state.get("max", 0.0))
        return h

    @classmethod
    def merge(cls, hists) -> "LatencyHistogram":
        """Merge histograms (objects or :meth:`state` dicts) into a new
        one. Bucket-exact: recorded into parts then merged equals
        recorded whole, except the quantile interpolation clamp, which
        uses the merged (global) max."""
        out = cls()
        for h in hists:
            if isinstance(h, dict):
                h = cls.from_state(h)
            for i, c in enumerate(h.counts):
                out.counts[i] += c
            out.n += h.n
            out.total += h.total
            if h.max > out.max:
                out.max = h.max
        return out


#: The step-time attribution ledger's named phases (ISSUE 8). Fixed so
#: dashboards, STEPTIME.json trends, and the gang aggregator never see a
#: phase they don't know:
#:   dispatch          — device train-step dispatch calls
#:   readback_harvest  — converting a dispatched group's result scalars
#:   producer_wait     — waiting on / running host batch production
#:   compact           — on-device subsample-compact passes (+ prefetch
#:                       dispatch)
#:   checkpoint        — snapshot copies, blocking saves, restores
#:   other             — explicitly-charged misc (corpus upload) plus
#:                       the wall-clock gap no span covered
LEDGER_PHASES = (
    "dispatch", "readback_harvest", "producer_wait", "compact",
    "checkpoint", "other",
)


class StepTimeLedger:
    """Step-time attribution for one fit: every accounted span charges
    its wall time to a named phase, replacing the single
    ``device_stall_seconds`` proxy with a breakdown that says WHERE the
    wall went (ISSUE 8). Fed by ``obs.ObsRun.span`` — the fit loops'
    existing span instrumentation — so when observability is off the
    cost is the NULL_SPAN path's single module-global read.

    Per phase: total seconds, span count, and a :class:`LatencyHistogram`
    of span durations (the gang aggregator merges these across ranks).
    Thread-safe; in practice only the fit thread accounts (ObsRun.span
    is a fit-loop hook — producer/writer threads use the module-level
    recorder hooks, which bypass the ledger by design so writer-thread
    time can never inflate a wall-clock-sum breakdown)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._t0 = time.time()
        self._t_end: Optional[float] = None
        self._seconds = {p: 0.0 for p in LEDGER_PHASES}
        self._counts = {p: 0 for p in LEDGER_PHASES}
        self._hists = {p: LatencyHistogram() for p in LEDGER_PHASES}
        #: Gang trace id (supervisor-minted, one per launch generation,
        #: ISSUE 18): rides the snapshot so the training/gang Prometheus
        #: steptime histograms can carry it as an exemplar pointing at
        #: the merged gang trace.
        self.trace_id: Optional[str] = os.environ.get("GLINT_TRACE_ID")

    def account(self, phase: str, seconds: float) -> None:
        with self._mu:
            self._seconds[phase] += seconds
            self._counts[phase] += 1
            self._hists[phase].record(seconds)

    def finalize(self) -> None:
        """Freeze the wall clock (run end); later snapshots stop growing
        ``other``. Idempotent — first call wins."""
        with self._mu:
            if self._t_end is None:
                self._t_end = time.time()

    def wall_seconds(self) -> float:
        with self._mu:
            return (self._t_end or time.time()) - self._t0

    def totals(self) -> Dict[str, float]:
        """{phase: seconds} with the unattributed wall gap folded into
        ``other`` — the phases sum to the ledger's wall clock."""
        snap = self.snapshot(include_hists=False)
        return {
            p: info["seconds"] for p, info in snap["phases"].items()
        }

    def snapshot(self, include_hists: bool = True) -> dict:
        """Full breakdown: wall, per-phase seconds/count (+ histogram
        state for cross-rank merging), and the unattributed gap, which
        is folded into ``other`` so the phase totals always sum to the
        wall clock."""
        with self._mu:
            wall = (self._t_end or time.time()) - self._t0
            accounted = sum(self._seconds.values())
            gap = max(0.0, wall - accounted)
            phases = {}
            for p in LEDGER_PHASES:
                info = {
                    "seconds": round(
                        self._seconds[p] + (gap if p == "other" else 0.0),
                        4,
                    ),
                    "count": self._counts[p],
                }
                if include_hists:
                    info["hist"] = self._hists[p].state()
                phases[p] = info
            out = {
                "wall_seconds": round(wall, 4),
                "accounted_seconds": round(accounted, 4),
                "unattributed_seconds": round(gap, 4),
                "phases": phases,
            }
            if self.trace_id:
                out["trace_id"] = self.trace_id
            return out

    def dump(self, path: str) -> None:
        """Write the per-run STEPTIME.json artifact (atomic): the phase
        breakdown plus per-phase span-duration quantiles, so bench
        trends can attribute a regression to a phase."""
        from glint_word2vec_tpu.utils import atomic_write_json

        snap = self.snapshot(include_hists=False)
        with self._mu:
            for p in LEDGER_PHASES:
                h = self._hists[p]
                snap["phases"][p].update(
                    p50_ms=round(h.quantile(0.50) * 1e3, 3),
                    p95_ms=round(h.quantile(0.95) * 1e3, 3),
                    p99_ms=round(h.quantile(0.99) * 1e3, 3),
                )
        snap["schema_version"] = 1
        atomic_write_json(path, snap)


class ServingMetrics:
    """Serving-path observability for ``serving.ModelServer``:
    per-endpoint latency histograms (p50/p95/p99), request/error
    counters, the coalesced-batch-size distribution, and the engine's
    query-shape compile counters — surfaced on ``/healthz`` and the
    ``/metrics`` endpoint. Thread-safe (the HTTP server is threaded)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._hist: Dict[str, LatencyHistogram] = {}
        self._errors: Dict[str, int] = {}
        self._batches: Dict[int, int] = {}
        #: Per-endpoint latency exemplar (ISSUE 18): the latest KEPT
        #: request trace's id + observed latency, so the Prometheus
        #: exposition can point a dashboard at a trace that actually
        #: exists in the span ring (tail sampling guarantees kept ids).
        self._exemplars: Dict[str, dict] = {}
        #: Optional obs.slo.SloEngine fed by :meth:`observe` (attached
        #: by the server, duck-typed here — utils must not import obs).
        self.slo = None
        #: Engine query-shape compiles at the end of server warmup;
        #: ``snapshot`` reports compiles past this as ``post_warmup``.
        self.warmup_compiles = 0
        self.cache_hits = 0
        self.cache_misses = 0
        # Overload-protection counters (ISSUE 7): sheds by reason,
        # deadline 504s, degraded-mode entries, in-flight peak.
        self.shed_admission = 0
        self.shed_degraded = 0
        self.deadline_hits = 0
        self.degraded_entered = 0
        self.inflight_peak = 0
        # Hot-swap accounting (ISSUE 10): table generations flipped
        # into the live engine by the snapshot watcher / /reload.
        self.table_swaps = 0
        self.swap_failures = 0
        #: Transient publish-dir read errors the snapshot watcher
        #: absorbed (backed off and retried instead of marking the
        #: generation failed).
        self.watch_errors = 0
        self.last_swap_time: Optional[float] = None
        #: Name of the generation currently served (None until the
        #: first swap names one — a freshly-loaded model predates the
        #: publish protocol's naming).
        self.generation: Optional[str] = None
        # ANN index accounting (ISSUE 12): refresh/build telemetry set
        # by the server on every index build (boot + each hot-swap),
        # query-side counters fed by the coalescer's dispatches.
        self.index_enabled = False
        self.index_refreshes = 0
        self.index_stats: dict = {}
        self.index_recall_at10: Optional[float] = None
        self.index_recall_gate_ok: Optional[bool] = None
        self.index_recall_gate: Optional[float] = None
        self.index_nprobe: Optional[int] = None
        self.last_index_refresh_time: Optional[float] = None
        self.ann_queries = 0
        self.ann_probes = 0
        self.exact_fallbacks: Dict[str, int] = {}

    #: Cap on distinct tracked endpoint paths: the key is the raw
    #: client-supplied request path, and without a bound a port scanner
    #: (or a path-building client bug) grows one persistent histogram
    #: per probe for the server's lifetime. Overflow aggregates under
    #: "_other". 64 >> the real endpoint count.
    MAX_PATHS = 64

    def observe(self, path: str, seconds: float, status: int = 200,
                trace_id: Optional[str] = None) -> None:
        with self._mu:
            h = self._hist.get(path)
            if h is None:
                if len(self._hist) >= self.MAX_PATHS:
                    path = "_other"
                    h = self._hist.get(path)
                if h is None:
                    h = self._hist[path] = LatencyHistogram()
            h.record(seconds)
            if status >= 400:
                self._errors[path] = self._errors.get(path, 0) + 1
            if trace_id:
                self._exemplars[path] = {
                    "trace_id": trace_id,
                    "value_ms": round(seconds * 1e3, 3),
                }
        slo = self.slo
        if slo is not None:
            # Outside the metrics lock: the engine has its own (fixed
            # lock order, no nesting).
            slo.observe(path, seconds, status)

    def record_batch(self, size: int) -> None:
        """One coalesced device dispatch of ``size`` queries."""
        with self._mu:
            self._batches[size] = self._batches.get(size, 0) + 1

    def record_cache(self, hit: bool) -> None:
        """One synonym result-cache lookup."""
        with self._mu:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1

    def record_shed(self, kind: str) -> None:
        """One shed request: ``"admission"`` (past the in-flight
        high-water mark, 429) or ``"degraded"`` (cache-only mode
        shedding a device-needing request, 429)."""
        with self._mu:
            if kind == "admission":
                self.shed_admission += 1
            else:
                self.shed_degraded += 1

    def record_deadline(self) -> None:
        """One request answered 504: its deadline passed before it
        could reach the device."""
        with self._mu:
            self.deadline_hits += 1

    def record_degraded_entered(self) -> None:
        """One transition INTO degraded cache-only mode."""
        with self._mu:
            self.degraded_entered += 1

    def record_inflight(self, n: int) -> None:
        """Track the admitted in-flight high-water mark."""
        with self._mu:
            if n > self.inflight_peak:
                self.inflight_peak = n

    def record_swap(self, generation: Optional[str] = None,
                    ok: bool = True) -> None:
        """One hot-swap attempt: ``ok`` flips the live generation,
        failure means the previous tables stayed live (staging or
        verification rejected the candidate)."""
        with self._mu:
            if ok:
                self.table_swaps += 1
                self.last_swap_time = time.time()
                if generation is not None:
                    self.generation = generation
            else:
                self.swap_failures += 1

    def record_watch_error(self) -> None:
        """One transient ``LATEST.json``/generation-dir read failure
        the snapshot watcher absorbed: it backed off and will retry on
        a later poll — the pointer was NOT marked failed and the
        watcher thread did not stall."""
        with self._mu:
            self.watch_errors += 1

    def record_index_refresh(self, stats: dict, recall: Optional[float],
                             gate_ok: Optional[bool], gate: float,
                             nprobe: int) -> None:
        """One index build/refresh (boot or hot-swap staging): the
        engine's ``ann_stats()`` dict plus the measured recall@10 of
        the approximate path against the exact path on the same tables
        and the pass/fail of the recall gate."""
        with self._mu:
            self.index_enabled = True
            self.index_refreshes += 1
            self.index_stats = dict(stats)
            self.index_recall_at10 = (
                # graftlint: ignore[sync-point] recall is a host float from the gate measurement
                round(float(recall), 4) if recall is not None else None
            )
            self.index_recall_gate_ok = gate_ok
            self.index_recall_gate = float(gate)  # graftlint: ignore[sync-point] host config scalar
            self.index_nprobe = int(nprobe)  # graftlint: ignore[sync-point] host config scalar
            self.last_index_refresh_time = time.time()

    def record_ann_query(self, n: int, nprobe: int) -> None:
        """``n`` queries answered through the coarse index, each
        probing ``nprobe`` clusters."""
        with self._mu:
            self.ann_queries += int(n)  # graftlint: ignore[sync-point] host batch-size count
            # graftlint: ignore[sync-point] host counts from the coalescer
            self.ann_probes += int(n) * int(nprobe)

    def record_exact_fallback(self, n: int, reason: str) -> None:
        """``n`` queries served by the EXACT path while the index is
        enabled: ``"requested"`` (the per-request ``exact=true`` escape
        hatch) or ``"gate"`` (the recall gate is failing, so the server
        held the approximate path back)."""
        with self._mu:
            self.exact_fallbacks[reason] = (
                # graftlint: ignore[sync-point] host batch-size count
                self.exact_fallbacks.get(reason, 0) + int(n)
            )

    def snapshot(self, total_compiles: int = 0,
                 checkpoint: Optional[dict] = None,
                 index_staleness: Optional[int] = None) -> dict:
        """``checkpoint`` is the engine's ``checkpoint_stats()`` dict
        (pending_async_saves / last_checkpoint_age_seconds /
        checkpoint_write_seconds); serving a freshly-loaded model reports
        Nones — the keys exist either way so dashboards never branch."""
        slo = self.slo
        slo_snap = slo.snapshot() if slo is not None else None
        with self._mu:
            endpoints = {}
            for path, h in sorted(self._hist.items()):
                endpoints[path] = {
                    "count": h.n,
                    "errors": self._errors.get(path, 0),
                    "p50_ms": round(h.quantile(0.50) * 1e3, 3),
                    "p95_ms": round(h.quantile(0.95) * 1e3, 3),
                    "p99_ms": round(h.quantile(0.99) * 1e3, 3),
                    "mean_ms": round(h.total / max(h.n, 1) * 1e3, 3),
                    "max_ms": round(h.max * 1e3, 3),
                    # Raw histogram state: quantiles cannot be merged,
                    # bucket counts can — the fleet aggregator combines
                    # replica snapshots exactly (obs.aggregate).
                    "hist": h.state(),
                }
                ex = self._exemplars.get(path)
                if ex:
                    endpoints[path]["exemplar"] = dict(ex)
            out = {
                "endpoints": endpoints,
                "coalesced_batch_sizes": {
                    str(k): v for k, v in sorted(self._batches.items())
                },
                "synonym_cache": {
                    "hits": self.cache_hits,
                    "misses": self.cache_misses,
                },
                "overload": {
                    "shed_admission_total": self.shed_admission,
                    "shed_degraded_total": self.shed_degraded,
                    "deadline_504_total": self.deadline_hits,
                    "degraded_entered_total": self.degraded_entered,
                    "inflight_peak": self.inflight_peak,
                },
                "compiles": {
                    "total": int(total_compiles),
                    "warmup": int(self.warmup_compiles),
                    "post_warmup": int(total_compiles)
                    - int(self.warmup_compiles),
                },
                "hot_swap": {
                    "table_swaps_total": self.table_swaps,
                    "swap_failures_total": self.swap_failures,
                    "watch_errors_total": self.watch_errors,
                    "last_swap_age_seconds": (
                        round(time.time() - self.last_swap_time, 2)
                        if self.last_swap_time else None
                    ),
                    "generation": self.generation,
                },
                "checkpoint": {
                    "pending_async_saves": (checkpoint or {}).get(
                        "pending_async_saves", 0
                    ),
                    "last_checkpoint_age_seconds": (checkpoint or {}).get(
                        "last_checkpoint_age_seconds"
                    ),
                    "checkpoint_write_seconds": (checkpoint or {}).get(
                        "checkpoint_write_seconds"
                    ),
                },
                "index": {
                    "enabled": self.index_enabled,
                    "clusters": self.index_stats.get("clusters"),
                    "member_slots": self.index_stats.get("member_slots"),
                    "nprobe": self.index_nprobe,
                    "build_seconds": self.index_stats.get("build_seconds"),
                    "spilled_rows": self.index_stats.get("spilled_rows"),
                    "updated_rows": self.index_stats.get("updated_rows"),
                    "refreshes_total": self.index_refreshes,
                    "last_refresh_age_seconds": (
                        round(time.time() - self.last_index_refresh_time, 2)
                        if self.last_index_refresh_time else None
                    ),
                    "recall_at10": self.index_recall_at10,
                    "recall_gate_ok": self.index_recall_gate_ok,
                    "recall_gate_threshold": self.index_recall_gate,
                    "ann_queries_total": self.ann_queries,
                    "probes_total": self.ann_probes,
                    "probes_per_query": (
                        round(self.ann_probes / self.ann_queries, 2)
                        if self.ann_queries else None
                    ),
                    "exact_fallbacks": dict(self.exact_fallbacks),
                    "table_versions_behind": index_staleness,
                },
            }
            if slo_snap is not None:
                out["slo"] = slo_snap
            return out


@contextlib.contextmanager
def profile_trace(out_dir: Optional[str]):
    """Capture a ``jax.profiler`` trace into ``out_dir`` (None = no-op).

    View with TensorBoard's profile plugin or xprof; the TPU-native answer
    to the reference having no profiling at all (SURVEY.md §5).
    """
    if not out_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(out_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
