"""Backend-platform selection that works under pinned platform lists.

``JAX_PLATFORMS`` is normally read once, as the *default* of the
``jax_platforms`` config value, when JAX's config initializes. Environments
that pre-register an accelerator backend at interpreter start (site hooks)
can pin the config past that point, after which the env var is silently
ignored — a plain ``JAX_PLATFORMS=cpu python ...`` then still blocks on the
accelerator tunnel. The fix is to re-assert the value through
``jax.config.update`` after importing jax; this helper is the one shared
implementation of that idiom (used by the CLI, bench.py, and scripts/).
"""

from __future__ import annotations

import os
from typing import Optional


def force_platform(platform: Optional[str] = None) -> None:
    """Make ``platform`` (or ``$JAX_PLATFORMS`` if None) authoritative.

    No-op when neither is set. Safe to call before any device use; must be
    called before the first ``jax.devices()``/computation to take effect.
    """
    p = platform or os.environ.get("JAX_PLATFORMS")
    if not p:
        return
    os.environ["JAX_PLATFORMS"] = p
    import jax

    jax.config.update("jax_platforms", p)
