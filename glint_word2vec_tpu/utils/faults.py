"""Deterministic fault injection: the seam the whole fault-domain layer
tests itself through.

The reference inherits a fault model from Akka (supervision trees,
delivery timeouts) but offers no way to *exercise* it — its integration
tests only ever run the happy path. Here every recovery path (supervisor
restart, checkpoint fallback, serving shed) is driven by the same named
injection points in unit tests, the CI chaos job, and
``scripts/chaos_drill.py``, so "we recover from a worker SIGKILL
mid-epoch" is an asserted property, not a hope.

Injection points are plain string names fired at the few places a fault
domain boundary exists:

  ``ckpt.pre_rename``    just before a snapshot directory's atomic commit
  ``ckpt.post_rename``   just after it
  ``worker.step``        once per dispatched training group (all fit loops)
  ``producer.batch``     once per assembled batch group (producer thread)
  ``serving.dispatch``   once per coalesced/simple serving device dispatch

Arming is via the ``GLINT_FAULTS`` environment variable (parsed once at
import) or :func:`arm` (tests). The spec grammar, ``;`` or ``,``
separated::

    point:action[@n]

      action := exc          raise FaultInjected at the point
              | kill         SIGKILL the current process (no cleanup,
                             no atexit — the honest crash)
              | hang[=secs]  sleep (default 3600s) — the hung-worker case
              | delay[=secs] sleep briefly (default 0.05s) then continue
      @n     := fire on the n-th hit of that point (1-based; default 1).
                The point keeps counting afterwards but fires only once.

    GLINT_FAULTS="worker.step:kill@120"          kill at the 120th group
    GLINT_FAULTS="ckpt.pre_rename:exc"           fail the next commit
    GLINT_FAULTS="producer.batch:hang@3;worker.step:delay=0.01"

Unarmed cost is one module-global ``is None`` check per ``fire`` call —
the points sit on per-group/per-dispatch paths, never per-pair.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

#: THE injection-point registry: name -> what the point means. Single
#: source of truth — ``parse_spec``/``arm`` validate specs against it
#: (a typo'd spec fails loudly instead of silently never firing),
#: ``fire`` rejects undeclared names at the call site, the README
#: fault-injection table is generated from these docstrings (asserted
#: by tests/test_analysis.py), and graftlint's fault-point rule holds
#: every ``faults.fire("...")`` literal in the codebase to this dict —
#: in both directions.
POINTS = {
    "ckpt.pre_rename":
        "just before a snapshot directory's atomic commit rename",
    "ckpt.post_rename":
        "just after the snapshot commit rename",
    "worker.step":
        "once per dispatched training group (all fit loops)",
    "producer.batch":
        "once per assembled batch group (producer thread)",
    "serving.dispatch":
        "once per coalesced/simple serving device dispatch",
    "publish.pre_commit":
        "just before a published generation directory's atomic rename",
    "publish.pre_pointer":
        "between the generation rename and the LATEST pointer flip",
    "serving.reload":
        "at the start of a serving hot-swap reload, before staging",
    "fleet.replica_probe":
        "once per fleet health probe of one serving replica",
    "fleet.rollout_step":
        "before each per-replica step of a rolling generation rollout",
    "exchange.pre_send":
        "just before a replica-exchange round's cross-rank transport",
    "ckpt.shard_commit":
        "after each checkpoint shard block + sidecar manifest write",
    "transform.producer":
        "once per packed bulk-transform batch (producer thread)",
    "transform.shard_commit":
        "after each bulk-transform vector shard + sidecar manifest "
        "commit",
    "fleet.shard_accept":
        "once per accepted connection on a balancer data-plane shard",
    "fleet.autoscale_step":
        "before each warm-spare autoscaler policy evaluation",
}

_ACTIONS = ("exc", "kill", "hang", "delay")


class FaultInjected(RuntimeError):
    """Raised by an armed ``exc`` fault — deliberately a RuntimeError so
    nothing in the stack catches it as an expected user error."""


class _Spec:
    __slots__ = ("point", "action", "arg", "at", "hits", "fired")

    def __init__(self, point: str, action: str, arg: Optional[float],
                 at: int):
        self.point = point
        self.action = action
        self.arg = arg
        self.at = at
        self.hits = 0
        self.fired = False


#: point -> armed spec; None when nothing is armed (the zero-cost path).
_ARMED: Optional[Dict[str, _Spec]] = None
_MU = threading.Lock()


def parse_spec(text: str) -> Dict[str, _Spec]:
    """Parse a ``GLINT_FAULTS`` spec string; raises ``ValueError`` with
    the offending clause on any grammar error."""
    out: Dict[str, _Spec] = {}
    for clause in text.replace(";", ",").split(","):
        clause = clause.strip()
        if not clause:
            continue
        try:
            point, _, rest = clause.partition(":")
            point = point.strip()
            if not rest:
                raise ValueError("missing action")
            action, _, at_s = rest.partition("@")
            at = int(at_s) if at_s else 1
            if at < 1:
                raise ValueError("@n must be >= 1")
            action, _, arg_s = action.partition("=")
            action = action.strip()
            arg = float(arg_s) if arg_s else None
            if point not in POINTS:
                raise ValueError(
                    f"unknown injection point {point!r} "
                    f"(valid: {', '.join(POINTS)})"
                )
            if action not in _ACTIONS:
                raise ValueError(
                    f"unknown action {action!r} "
                    f"(valid: {', '.join(_ACTIONS)})"
                )
        except ValueError as e:
            raise ValueError(f"bad GLINT_FAULTS clause {clause!r}: {e}")
        out[point] = _Spec(point, action, arg, at)
    return out


def arm(text: Optional[str]) -> None:
    """Arm from a spec string (None/empty disarms). Replaces any
    previously armed set wholesale."""
    global _ARMED
    specs = parse_spec(text) if text else {}
    with _MU:
        _ARMED = specs or None
    if specs:
        logger.warning(
            "fault injection ARMED: %s",
            "; ".join(
                f"{s.point}:{s.action}@{s.at}" for s in specs.values()
            ),
        )


def disarm() -> None:
    arm(None)


def armed() -> bool:
    return _ARMED is not None


def fire(point: str) -> None:
    """Hit one injection point. Free (one global read) when unarmed.

    ``point`` must be declared in :data:`POINTS` — an undeclared name
    raises ``ValueError`` as soon as any fault is armed, so a typo'd
    call site cannot silently never fire against its armed spec."""
    if _ARMED is None:
        return
    if point not in POINTS:
        raise ValueError(
            f"undeclared injection point {point!r} fired "
            f"(valid: {', '.join(sorted(POINTS))}) — declare it in "
            f"utils.faults.POINTS"
        )
    with _MU:
        spec = _ARMED.get(point) if _ARMED is not None else None
        if spec is None:
            return
        spec.hits += 1
        if spec.fired or spec.hits != spec.at:
            return
        spec.fired = True
    logger.error(
        "fault injection FIRING %s:%s at hit %d",
        point, spec.action, spec.at,
    )
    if spec.action == "exc":
        raise FaultInjected(f"injected fault at {point} (hit {spec.at})")
    if spec.action == "kill":
        # The honest crash: no cleanup, no Python teardown, no flushed
        # buffers — exactly what a preempted/OOM-killed worker leaves.
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)  # pragma: no cover — never survives the signal
    if spec.action == "hang":
        time.sleep(spec.arg if spec.arg is not None else 3600.0)
        return
    if spec.action == "delay":
        time.sleep(spec.arg if spec.arg is not None else 0.05)
        return


# Arm from the environment once at import: workers launched by the
# supervisor (or the chaos drill) inherit their schedule with no code
# changes anywhere.
_env_spec = os.environ.get("GLINT_FAULTS")
if _env_spec:
    arm(_env_spec)
