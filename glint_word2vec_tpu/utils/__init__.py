"""Utilities: parameter validation, logging/metrics, checkpointing."""

import json as _json
import os as _os


def atomic_write_json(path: str, obj, **dump_kwargs) -> None:
    """Write JSON via temp file + ``os.replace`` so a crash mid-write can
    never leave a truncated document behind (readers either see the old
    file or the complete new one). Shared by the metrics dump, the obs
    status-file mirror, the Chrome-trace export, and the scripts/ bench
    artifact writers (which pass ``indent=2`` through ``dump_kwargs``)."""
    tmp = f"{path}.tmp.{_os.getpid()}"
    with open(tmp, "w") as f:
        _json.dump(obj, f, **dump_kwargs)
    _os.replace(tmp, path)


def atomic_write_text(path: str, text: str, encoding: str = "utf-8") -> None:
    """Write a text file via temp + ``os.replace`` — same crash contract
    as :func:`atomic_write_json` for line-oriented files (words lists)."""
    tmp = f"{path}.tmp.{_os.getpid()}"
    with open(tmp, "w", encoding=encoding) as f:
        f.write(text)
    _os.replace(tmp, path)


def atomic_write_npy(path: str, arr) -> None:
    """``np.save`` via temp file + ``os.replace`` — the array twin of
    :func:`atomic_write_json`: readers either see the previous complete
    array or the new complete one, never a truncated ``.npy``. Writes
    through a file object so numpy cannot append a second ``.npy``
    suffix to the temp name."""
    import numpy as _np

    tmp = f"{path}.tmp.{_os.getpid()}"
    with open(tmp, "wb") as f:
        _np.save(f, arr)
    _os.replace(tmp, path)


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (1 for n <= 1). The shape-bucket
    quantizer for the serving hot path: padding device dispatches to
    power-of-two sizes bounds the jit-compiled shape family to
    log2(max) members instead of one compile per distinct request
    shape (Shazeer et al. 1602.02215's fixed-shape batched-dispatch
    argument restated for XLA)."""
    n = int(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()
