"""Utilities: parameter validation, logging/metrics, checkpointing."""

import json as _json
import os as _os


def atomic_write_json(path: str, obj) -> None:
    """Write JSON via temp file + ``os.replace`` so a crash mid-write can
    never leave a truncated document behind (readers either see the old
    file or the complete new one). Shared by the metrics dump, the obs
    status-file mirror, and the Chrome-trace export."""
    tmp = f"{path}.tmp.{_os.getpid()}"
    with open(tmp, "w") as f:
        _json.dump(obj, f)
    _os.replace(tmp, path)


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (1 for n <= 1). The shape-bucket
    quantizer for the serving hot path: padding device dispatches to
    power-of-two sizes bounds the jit-compiled shape family to
    log2(max) members instead of one compile per distinct request
    shape (Shazeer et al. 1602.02215's fixed-shape batched-dispatch
    argument restated for XLA)."""
    n = int(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()
