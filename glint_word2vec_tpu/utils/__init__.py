"""Utilities: parameter validation, logging/metrics, checkpointing."""
