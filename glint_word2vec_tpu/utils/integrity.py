"""Checkpoint integrity: manifests, verification, and committed-state
resolution with fallback.

PR 5's commit protocol made checkpoints crash-*safe* (an interrupted
write can never be referenced by ``train_state.json``); this module makes
them crash-*detectable* and *recoverable*. Every fresh snapshot directory
carries a ``manifest.json`` — per-file sha256 + byte sizes + the engine
``table_version`` at snapshot time — written inside the temp directory
BEFORE the atomic rename, so a committed directory is verifiable end to
end and a directory missing its manifest is, by construction, either
legacy (pre-manifest) or partial. ``verify_snapshot_dir`` checks a
directory against its manifest; ``resolve_train_state`` walks the
``train_state.json`` chain (current -> previous committed record, the
keep-last-2 retention) and returns the newest record whose snapshot
verifies, logging one clean line per rejected candidate — bit rot or a
half-written directory becomes a fallback, not a silent load of garbage.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"

#: Sidecar manifest of ONE shard file (ISSUE 15 shard-streaming
#: checkpoints): ``<shard>.npy`` pairs with ``<shard>.npy.manifest.json``
#: written by whichever rank produced the shard — no single writer ever
#: needs to see (or hash) the whole table, so integrity survives the
#: rank-parallel multi-host save path that version-1 manifests could not
#: cover (they were deleted there). The top-level ``manifest.json``
#: (version 2) lists shard files by NAME under ``shard_files`` and keeps
#: inline hashes only for the small non-shard files it wrote itself.
SHARD_MANIFEST_SUFFIX = ".manifest.json"


class CheckpointCorruptError(RuntimeError):
    """A snapshot directory failed integrity verification (missing
    files, size/hash mismatch, unparseable manifest, or partial dir)."""


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def build_manifest(
    dirpath: str, fnames: List[str], table_version: Optional[int] = None,
    table_dtype: Optional[str] = None,
) -> dict:
    """Hash + size every named file in ``dirpath`` into a manifest dict.
    Runs on the checkpoint writer thread (async saves) — a streaming
    read pass per file, cheap next to the durability fsyncs.

    ``table_dtype`` records the engine's STORAGE dtype ("float32" |
    "bfloat16") at snapshot time (ISSUE 11 bf16 tables): the .npy
    payloads are always fp32 on disk (numpy has no bf16), so the
    manifest is where a loader/auditor sees what precision the values
    were rounded to before the upcast — engine.json's "dtype" is the
    authoritative engine-rebuild field; this copy makes the integrity
    artifact self-describing."""
    files: Dict[str, dict] = {}
    for fname in fnames:
        p = os.path.join(dirpath, fname)
        files[fname] = {
            "sha256": _sha256_file(p),
            "size": os.path.getsize(p),
        }
    return {
        "version": 1,
        "table_version": table_version,
        "table_dtype": table_dtype,
        "files": files,
    }


def write_manifest(dirpath: str, manifest: dict, *,
                   fsync: bool = True) -> None:
    """Write ``manifest.json`` into ``dirpath`` (atomic replace +
    optional fsync, same durability contract as the snapshot files)."""
    tmp = os.path.join(dirpath, f"{MANIFEST_NAME}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dirpath, MANIFEST_NAME))


def build_shard_manifest(dirpath: str, fname: str,
                         table_version: Optional[int] = None) -> dict:
    """Hash + size ONE shard file into its sidecar manifest dict. Runs
    on whichever thread/rank wrote the shard — one streaming read of
    that shard alone, never a table gather."""
    p = os.path.join(dirpath, fname)
    return {
        "version": 1,
        "table_version": table_version,
        "file": {"sha256": _sha256_file(p), "size": os.path.getsize(p)},
    }


def write_shard_manifest(dirpath: str, fname: str, manifest: dict, *,
                         fsync: bool = True) -> None:
    """Write ``<fname>.manifest.json`` next to its shard (atomic
    replace + optional fsync — the shard's durability contract)."""
    out = os.path.join(dirpath, fname + SHARD_MANIFEST_SUFFIX)
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, out)


def _verify_shard(path: str, fname: str, *, deep: bool) -> None:
    """Verify one shard file against its sidecar manifest; raises
    :class:`CheckpointCorruptError` naming the shard on any mismatch."""
    fp = os.path.join(path, fname)
    mp = fp + SHARD_MANIFEST_SUFFIX
    if not os.path.exists(fp):
        raise CheckpointCorruptError(f"{path}: missing shard {fname}")
    if not os.path.exists(mp):
        raise CheckpointCorruptError(
            f"{path}: shard {fname} has no sidecar manifest"
        )
    try:
        with open(mp) as f:
            ent = json.load(f)["file"]
    except (ValueError, KeyError, OSError) as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable shard manifest for {fname} ({e})"
        )
    size = os.path.getsize(fp)
    if size != ent["size"]:
        raise CheckpointCorruptError(
            f"{path}: shard {fname} is {size} bytes, its manifest says "
            f"{ent['size']}"
        )
    if deep and _sha256_file(fp) != ent["sha256"]:
        raise CheckpointCorruptError(
            f"{path}: shard {fname} sha256 mismatch (bit rot or torn "
            f"write)"
        )


def verify_shard(path: str, fname: str, *, deep: bool = True) -> None:
    """Public single-shard verification seam: one shard file against its
    sidecar manifest, raising :class:`CheckpointCorruptError` on any
    mismatch. ``deep=False`` checks existence + byte size only (the
    cheap liveness check); ``deep=True`` re-hashes the payload. Used by
    the bulk-transform resume scan (``glint_word2vec_tpu.batch``), which
    trusts exactly the committed-shard prefix that verifies — the same
    contract the checkpoint restore path applies via
    :func:`verify_snapshot_dir`."""
    _verify_shard(path, fname, deep=deep)


def verify_snapshot_dir(path: str, *, deep: bool = True) -> bool:
    """Verify a snapshot directory against its manifest.

    Returns True when the manifest exists and every entry matches
    (existence + size always; sha256 when ``deep``), False for a legacy
    directory with no manifest (loadable, just unverifiable — manifests
    arrived after round 6). Raises :class:`CheckpointCorruptError` with
    a one-line reason on any mismatch or on a partial/unreadable
    directory. ``GLINT_CKPT_NO_VERIFY=1`` skips hashing (size checks
    only) for giant tables on slow disks."""
    if not os.path.isdir(path):
        raise CheckpointCorruptError(f"{path}: not a directory")
    if not os.path.exists(os.path.join(path, "engine.json")):
        raise CheckpointCorruptError(
            f"{path}: partial snapshot (no engine.json)"
        )
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return False
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        entries = manifest["files"]
    except (ValueError, KeyError, OSError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable manifest ({e})")
    if os.environ.get("GLINT_CKPT_NO_VERIFY", "0") == "1":
        deep = False
    # Version-2 manifests (ISSUE 15): table shard files are listed by
    # name and carry their own sidecar manifests — each was hashed by
    # the rank that wrote it, so the whole-directory verify here is the
    # sum of per-shard verifies, still without any full-table read into
    # one buffer.
    for fname in manifest.get("shard_files", ()):
        _verify_shard(path, fname, deep=deep)
    for fname, ent in entries.items():
        fp = os.path.join(path, fname)
        if not os.path.exists(fp):
            raise CheckpointCorruptError(f"{path}: missing file {fname}")
        size = os.path.getsize(fp)
        if size != ent["size"]:
            raise CheckpointCorruptError(
                f"{path}: {fname} is {size} bytes, manifest says "
                f"{ent['size']}"
            )
        if deep and _sha256_file(fp) != ent["sha256"]:
            raise CheckpointCorruptError(
                f"{path}: {fname} sha256 mismatch (bit rot or torn write)"
            )
    return True


def resolve_train_state(
    checkpoint_dir: str, *, deep: bool = True
) -> Optional[Tuple[dict, str]]:
    """Resolve the newest *verifiable* committed checkpoint.

    Reads ``train_state.json`` and tries, in order, the current record
    and then its embedded ``prev`` record (the previous committed
    checkpoint, kept by the keep-last-2 retention). Returns
    ``(state_record, snapshot_path)`` for the first candidate whose
    directory verifies, logging ONE clean line per rejected candidate;
    ``None`` when there is no state file (a fresh run); a legacy record
    without a ``"ckpt"`` key comes back as ``(record, None)``. Raises
    :class:`CheckpointCorruptError` when a state file exists but no
    candidate verifies — resuming from scratch silently would retrain
    over hours of committed progress."""
    state_path = os.path.join(checkpoint_dir, "train_state.json")
    if not os.path.exists(state_path):
        return None
    with open(state_path) as f:
        state = json.load(f)
    if "ckpt" not in state:
        # Legacy (pre-snapshot-dir) layout: nothing to verify and no
        # fallback chain — hand the record back for the caller's
        # legacy-load path (path is None: no snapshot directory).
        return {k: v for k, v in state.items() if k != "prev"}, None
    candidates = [state]
    prev = state.get("prev")
    if prev and prev.get("ckpt"):
        candidates.append(prev)
    reasons = []
    for i, rec in enumerate(candidates):
        ck_path = os.path.join(checkpoint_dir, rec["ckpt"])
        try:
            verify_snapshot_dir(ck_path, deep=deep)
        except CheckpointCorruptError as e:
            reasons.append(str(e))
            logger.error(
                "checkpoint %s failed integrity verification (%s)%s",
                rec["ckpt"], e,
                "; falling back to the previous committed snapshot"
                if i + 1 < len(candidates) else "",
            )
            continue
        if i > 0:
            logger.warning(
                "resuming from fallback checkpoint %s (epoch %s): the "
                "newest committed snapshot did not verify",
                rec["ckpt"], rec.get("epochs_completed"),
            )
        return {k: v for k, v in rec.items() if k != "prev"}, ck_path
    raise CheckpointCorruptError(
        f"no verifiable committed checkpoint in {checkpoint_dir}: "
        + " | ".join(reasons)
    )
