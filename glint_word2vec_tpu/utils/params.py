"""Hyperparameter surface with reference-parity validation.

The reference exposes these as Spark ML ``Params`` with validators
(ml/feature/ServerSideGlintWord2Vec.scala:40-222) mirrored by fluent setters
with ``require`` guards on the MLlib trainer (mllib:92-243) and by 11 py4j
``Param``s in the Python bindings (ml_glintword2vec.py:101-136). Defaults here
are the reference defaults (mllib:67-81; SURVEY.md §5 config tier 1).

Parameters that exist only to describe the Spark/Akka deployment
(``numParameterServers``, ``parameterServerHost``, ``parameterServerConfig``,
``unigramTableSize``) are replaced by mesh geometry: ``num_shards`` is the
model-axis size of the TPU mesh (the direct analogue of the number of
parameter servers — each shard owns ``1/num_shards`` of both matrices,
README.md:69) and ``num_partitions`` maps to the data-parallel axis. The
Akka message-size guard ``batchSize*n*window <= 10000`` (mllib:154-156) has no
TPU analogue — there is no message ceiling on ICI — so it is intentionally NOT
enforced (documented divergence); batch geometry is limited by HBM only.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(msg)


@dataclass
class Word2VecParams:
    """All training/serving hyperparameters, validated on construction.

    Attributes (reference param in parens):
      vector_size: embedding dimension d (``vectorSize``, default 100).
      window: max context window (``windowSize``, default 5).
      step_size: initial learning rate (``stepSize``, default 0.01875).
      batch_size: center positions per minibatch (``batchSize``, default 50 in
        the reference; here defaults to 1024 — a TPU-shaped batch. 50-position
        batches underutilize the chip; quality at larger sync batches is
        validated by the analogy gates).
      num_negatives: negatives per (center, context) pair (``n``, default 5).
      subsample_ratio: frequency subsampling ratio (``subsampleRatio``).
        The reference *declares* a default of 1e-6 (mllib:75) but its
        integer-division bug (mllib:375) makes subsampling a no-op, so the
        reference's de-facto default is "disabled" — and 1e-6 under the
        *correct* formula discards ~95% of a typical corpus. We therefore
        default to 0 (disabled, the de-facto reference behavior) and users
        opting in get the fixed semantics (typical useful values 1e-3..1e-5;
        see Vocabulary.keep_probabilities).
      min_count: minimum token frequency (``minCount``, default 5).
      num_iterations: epochs (``maxIter``/``numIterations``, default 1).
      max_sentence_length: sentence chunk bound (``maxSentenceLength``, 1000).
      seed: RNG seed for subsampling/windowing/negatives/init (``seed``).
      num_partitions: data-parallel axis size (``numPartitions``, default 1).
      num_shards: model-parallel axis size; 1/num_shards of the vocab rows of
        syn0/syn1 live on each mesh slice (``numParameterServers``, default 5
        in the reference; default 1 here — set from the mesh).
      unigram_power: noise-distribution exponent (fixed 0.75 in word2vec).
      unigram_table_size: optional quantized-table compatibility mode
        (``unigramTableSize``; None = exact alias sampling, see corpus.alias).
      dtype: parameter dtype for the embedding tables ("float32" or
        "bfloat16"). Dots/updates always accumulate in float32.
      steps_per_call: minibatches executed per device dispatch (an on-device
        ``lax.scan`` over stacked batches). The TPU analogue of the
        reference's RPC flow control — it kept ~1 minibatch in flight per
        worker (mllib:419-429); here each dispatch carries this many, so
        host round-trip latency amortizes away. 1 = step-at-a-time.
      shared_negatives: 0 (default) draws ``num_negatives`` fresh noise
        words per (center, context) pair — the reference's server-side
        semantics (mllib:420-421). > 0 draws ONE pool of this many noise
        words per step, shared across the batch and weighted to the same
        expected gradient (ops.sgns.shared_sgns_grads) — the TPU-shaped
        estimator: dense MXU matmuls instead of batch*contexts*n sparse
        row accesses. 1024-8192 are typical pool sizes.
    """

    vector_size: int = 100
    window: int = 5
    step_size: float = 0.01875
    batch_size: int = 1024
    num_negatives: int = 5
    subsample_ratio: float = 0.0
    min_count: int = 5
    num_iterations: int = 1
    max_sentence_length: int = 1000
    seed: int = 1
    num_partitions: int = 1
    num_shards: int = 1
    unigram_power: float = 0.75
    unigram_table_size: int | None = None
    dtype: str = "float32"
    #: MXU operand dtype for the train step's dense contractions (f32
    #: accumulation either way): "float32" = exactness-tested numerics,
    #: "bfloat16" = the MXU-native fast path (ops/sgns.py). None defers to
    #: the engine's GLINT_W2V_MATMUL_DTYPE env default (so the env knob
    #: works through the model/CLI path too).
    compute_dtype: str | None = None
    #: Model-axis table partitioning: "rows" (vocab rows split 1/n) or
    #: "dims" (every shard holds all rows x 1/n of the columns — the
    #: CIKM'16 column partitioning; model-axis traffic becomes scalar
    #: logit partials). See parallel/engine.py.
    layout: str = "rows"
    steps_per_call: int = 16
    shared_negatives: int = 0
    #: Device-resident corpus dispatch shape: "dense" (the default since
    #: ISSUE 11) prefix-sum-compacts the valid (center, context) pairs
    #: into dense fixed-shape pair batches before the update
    #: (ops/device_batching.pack_window_pairs), spending ~every
    #: dispatched FLOP on a real pair — and is the only shape the fused
    #: Pallas megakernel (ops/pallas_sgns) accelerates. "grid" restores
    #: the legacy (batch, context) window grids — the reference's shape,
    #: ~43% live lanes at window 5 — for A/B comparison or to resume an
    #: old grid-written mid-epoch checkpoint. Same valid-pair multiset
    #: per epoch either way (window draws reproduce the grid mapping);
    #: negative/loss RNG streams differ like host-vs-device already do.
    #: Ignored (with a log line) when training routes to the host
    #: batcher, which always builds grid-shaped batches.
    batch_packing: str = "dense"
    #: Cross-replica reconciliation for multi-process training (ISSUE
    #: 15, parallel/exchange.py). "none" (default) keeps the SPMD
    #: global-mesh path (batch payloads exchanged inside every jitted
    #: step). "sparse" switches pc > 1 runs to data-parallel replicas:
    #: each process trains its corpus shard on a LOCAL mesh and ships
    #: only (touched row ids, accumulated fp32 deltas) after every
    #: dispatch group — wire cost scales with rows touched, not vocab
    #: size. "dense" ships full per-rank table deltas on the same
    #: cadence (the parity baseline / escape hatch; also forced by
    #: GLINT_DENSE_EXCHANGE=1 or any capacity overflow, per round).
    exchange: str = "none"
    #: Fixed touched-row buffer capacity per sync (0 = auto-size from
    #: the dispatch-group pair budget, then adapt down from the
    #: observed touched-row high-water mark; see
    #: exchange.default_capacity. A nonzero value PINS the capacity —
    #: no adaptation). Constant shapes keep the protocol compile-once.
    exchange_capacity: int = 0
    #: Sparse exchange payload encoding (ISSUE 16): "fp32" (exact),
    #: "bf16" (half the payload, one rounding per component), or
    #: "int8" (per-row maxabs scale + error feedback — the local
    #: quantization residual folds into the next round, keeping the
    #: update stream unbiased). Dense/spill/flush rounds always ship
    #: exact fp32. Ignored unless exchange="sparse".
    exchange_wire: str = "fp32"
    #: Round coalescing (ISSUE 16): run a wire round every R dispatch
    #: groups instead of every group — repeated touches of a hot row
    #: within the window cost one wire row. 1 = sync every group (the
    #: PR 15 cadence).
    exchange_every: int = 1
    #: Exchange sync topology (ISSUE 16): "flat" allgathers every
    #: rank's payload (the PR 15 protocol); "twolevel" ships exact
    #: fp32 sparse payloads on the fast intra-node hop, folds them
    #: into one node delta, and only node LEADERS ship the quantized
    #: node payload over the slow inter-node hop (Ji et al.
    #: arXiv:1604.04661; GLINT_RANKS_PER_NODE sets the node size).
    exchange_topology: str = "flat"
    #: Replica corpus sharding: "roundrobin" (the PR 15 interleave) or
    #: "locality" — sentences clustered by their rarest token so each
    #: replica's touched-row set concentrates, shrinking the
    #: touched-row unions that size every exchange buffer
    #: (arXiv:1909.03359).
    exchange_shard: str = "roundrobin"

    def __post_init__(self) -> None:
        self.validate()

    def validate(self) -> None:
        _require(self.vector_size > 0, "vector_size must be > 0")
        _require(self.window > 0, "window must be > 0")
        _require(self.step_size > 0, "step_size must be > 0")
        _require(self.batch_size > 0, "batch_size must be > 0")
        _require(self.num_negatives > 0, "num_negatives must be > 0")
        _require(self.subsample_ratio >= 0, "subsample_ratio must be >= 0")
        _require(self.min_count >= 0, "min_count must be >= 0")
        _require(self.num_iterations > 0, "num_iterations must be > 0")
        _require(self.max_sentence_length > 0, "max_sentence_length must be > 0")
        _require(self.num_partitions > 0, "num_partitions must be > 0")
        _require(self.num_shards > 0, "num_shards must be > 0")
        _require(0 < self.unigram_power <= 1, "unigram_power must be in (0, 1]")
        _require(
            self.unigram_table_size is None or self.unigram_table_size > 0,
            "unigram_table_size must be > 0 or None",
        )
        _require(self.dtype in ("float32", "bfloat16"), "dtype must be float32|bfloat16")
        _require(
            self.compute_dtype in (None, "float32", "bfloat16"),
            "compute_dtype must be float32|bfloat16|None",
        )
        _require(
            self.layout in ("rows", "dims"), "layout must be rows|dims"
        )
        _require(self.steps_per_call > 0, "steps_per_call must be > 0")
        _require(self.shared_negatives >= 0, "shared_negatives must be >= 0")
        _require(
            self.batch_packing in ("grid", "dense"),
            "batch_packing must be grid|dense",
        )
        _require(
            self.exchange in ("none", "sparse", "dense"),
            "exchange must be none|sparse|dense",
        )
        _require(
            self.exchange_capacity >= 0, "exchange_capacity must be >= 0"
        )
        _require(
            self.exchange_wire in ("fp32", "bf16", "int8"),
            "exchange_wire must be fp32|bf16|int8",
        )
        _require(self.exchange_every >= 1, "exchange_every must be >= 1")
        _require(
            self.exchange_topology in ("flat", "twolevel"),
            "exchange_topology must be flat|twolevel",
        )
        _require(
            self.exchange_shard in ("roundrobin", "locality"),
            "exchange_shard must be roundrobin|locality",
        )

    def replace(self, **kwargs) -> "Word2VecParams":
        return dataclasses.replace(self, **kwargs)

    def to_json(self) -> str:
        """Persistence metadata, analogous to DefaultParamsWriter metadata +
        the custom JSON codec for the PS config param (ml:183-195, 504-507)."""
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Word2VecParams":
        return cls(**json.loads(s))
