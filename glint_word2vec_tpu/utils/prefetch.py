"""Host->device infeed pipelining: background batch prefetch.

The reference keeps exactly one minibatch RPC chain in flight per worker
(the sequential ``Await`` over dotprod->adjust futures, mllib:419-429;
SURVEY.md §2.3 "async pipelining"). The TPU equivalent: JAX dispatch is
already asynchronous (the Python loop runs ahead of the device), so the
only serial gap left is *producing* the next batch on host. This module
moves that production to a daemon thread with a small bounded queue,
overlapping the native windowing pass with device execution — the
double-buffered infeed of SURVEY.md §7 step 3.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, TypeVar

T = TypeVar("T")

_SENTINEL = object()


def prefetch(it: Iterator[T], depth: int = 2) -> Iterator[T]:
    """Iterate ``it`` on a daemon thread, keeping up to ``depth`` items
    ready. Exceptions in the producer are re-raised at the consumer."""
    if depth <= 0:
        yield from it
        return
    q: queue.Queue = queue.Queue(maxsize=depth)
    err: list[BaseException] = []
    stop = threading.Event()

    def _put(item) -> bool:
        # Bounded put that notices consumer abandonment, so an abandoned
        # prefetch never leaves the thread blocked forever on a full queue
        # (pinning the source iterator and its buffers).
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for item in it:
                if not _put(item):
                    return
        except BaseException as e:  # re-raised on the consumer side
            err.append(e)
        finally:
            _put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True, name="batch-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        # Consumer done or abandoned (exception/GeneratorExit upstream):
        # release the producer and drop buffered items.
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
