"""Generation publish protocol: how a streaming trainer hands model
snapshots to a serving fleet it never talks to directly.

Layout of a publish directory::

    publish_dir/
      gen-000001/           # one committed generation = a loadable
        matrix/             #   model dir (engine shards + manifest,
        words.txt           #   grown word list, params metadata)
        params.json
      gen-000002/
      LATEST.json           # the pointer: {"generation": "gen-000002",
                            #  "seq": 2, "published_unix": ..., ...}

Commit protocol (the PR 5 temp+rename discipline, one level up):

1. Everything lands in ``gen-NNNNNN.tmp-<pid>`` first. The matrix goes
   through ``engine.save``'s own fsync'd temp+rename (so it carries the
   PR 7 integrity manifest); ``words.txt``/``params.json`` are atomic
   writes.
2. ONE ``os.replace`` renames the temp directory to ``gen-NNNNNN`` —
   the generation now exists, complete by construction.
3. ``LATEST.json`` is atomically replaced to reference it.

A watcher trusts ONLY ``LATEST.json``: a trainer SIGKILLed before step
2 leaves an ignored ``*.tmp-*`` orphan (pruned on the next trainer
start); killed between 2 and 3 leaves a complete-but-unreferenced
generation the next trainer run simply numbers past. Fault points
``publish.pre_commit`` / ``publish.pre_pointer`` (utils/faults.py) sit
exactly on those two windows so the crash cases are drills, not hopes.

Retention keeps the last ``keep`` committed generations: a replica may
still be staging generation N-1 off the request path while N commits,
so the floor is 2.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import time
from typing import Optional

from glint_word2vec_tpu.utils import atomic_write_json, atomic_write_text, faults

logger = logging.getLogger(__name__)

LATEST_NAME = "LATEST.json"

_GEN_RE = re.compile(r"^gen-(\d{6,})$")


def generation_name(seq: int) -> str:
    """``gen-000042`` for seq 42 — zero-padded so lexical order is
    publication order."""
    return f"gen-{int(seq):06d}"


def read_latest(publish_dir: str,
                raise_errors: bool = False) -> Optional[dict]:
    """The ``LATEST.json`` pointer dict, or None when absent/unreadable.
    The pointer is atomically replaced, so a local reader can never see
    a torn write — an unparseable file means a foreign artifact, logged
    once per distinct error and treated as absent.

    ``raise_errors=True`` surfaces read/parse failures as the
    ``OSError``/``ValueError`` they are instead of folding them into
    "absent": on network filesystems a pointer read CAN fail or tear
    transiently (mid-rename visibility, NFS attribute-cache hiccups),
    and a caller with retry machinery — the serving
    ``SnapshotWatcher``, the fleet rollout coordinator — wants to count
    and back off rather than silently treat the hiccup as "no publish
    yet". A genuinely missing pointer (``FileNotFoundError``) is the
    normal no-publish-yet state and stays None in both modes."""
    path = os.path.join(publish_dir, LATEST_NAME)
    try:
        with open(path) as f:
            latest = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        if raise_errors:
            raise
        logger.warning("unreadable %s: %s", path, e)
        return None
    if not isinstance(latest, dict) or "generation" not in latest:
        if raise_errors:
            raise ValueError(f"malformed {path}: {latest!r}")
        logger.warning("malformed %s: %r", path, latest)
        return None
    return latest


def resolve_latest(publish_dir: str) -> Optional[str]:
    """Absolute path of the generation ``LATEST.json`` references, or
    None when there is no committed generation. The pointer flips only
    after the generation's atomic rename, so a referenced directory
    exists and is complete; a missing one means an operator deleted it
    — surfaced as absent, never an exception."""
    latest = read_latest(publish_dir)
    if latest is None:
        return None
    gen_dir = os.path.join(publish_dir, str(latest["generation"]))
    if not os.path.isdir(gen_dir):
        logger.warning(
            "%s references missing generation %r", LATEST_NAME,
            latest["generation"],
        )
        return None
    return gen_dir


def discover_model_publish_dirs(root: str) -> "dict":
    """Map model-id -> publish dir under a catalog root (ISSUE 20).

    Layout: each immediate subdirectory of ``root`` that carries a
    ``LATEST.json`` pointer is one model's publish directory, and the
    subdirectory name is the model id — so a streaming trainer per
    model publishes independently and the multi-model server (or the
    fleet's per-model rollout coordinators) watches the whole catalog
    from one ``--watch-models`` root. Subdirectories without a pointer
    (a trainer that has not committed its first generation yet) are
    skipped; a later discovery pass picks them up."""
    out: dict = {}
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for name in names:
        d = os.path.join(root, name)
        if os.path.isfile(os.path.join(d, LATEST_NAME)):
            out[name] = d
    return out


def next_generation_seq(publish_dir: str) -> int:
    """1 + the highest committed generation number on disk (orphaned
    post-crash generations included, so a restarted trainer never
    reuses — and thereby never clobbers — a directory a replica might
    be reading)."""
    top = 0
    try:
        entries = os.listdir(publish_dir)
    except FileNotFoundError:
        return 1
    for entry in entries:
        m = _GEN_RE.match(entry)
        if m:
            top = max(top, int(m.group(1)))
    return top + 1


def prune_orphan_tmp(publish_dir: str) -> int:
    """Remove ``*.tmp-*`` directories a crashed publish left behind
    (they were never referenced; a concurrent live publisher uses its
    own pid suffix). Returns the count removed."""
    n = 0
    try:
        entries = os.listdir(publish_dir)
    except FileNotFoundError:
        return 0
    for entry in entries:
        if ".tmp-" in entry:
            shutil.rmtree(
                os.path.join(publish_dir, entry), ignore_errors=True
            )
            n += 1
    return n


class SnapshotPublisher:
    """Publishes committed model generations from a live engine.

    ``publish()`` snapshots the tables on the calling thread (the same
    device->host copy ``save_async`` charges the trainer) and runs the
    serialization + the whole commit sequence on the engine's single
    checkpoint writer thread, so the trainer returns to dispatching
    immediately. Commits are strictly ordered through that writer —
    ``LATEST.json`` can never flip backwards."""

    def __init__(self, publish_dir: str, engine, params, *,
                 keep: int = 3):
        self.publish_dir = publish_dir
        self.engine = engine
        self.params = params
        self.keep = max(2, int(keep))
        os.makedirs(publish_dir, exist_ok=True)
        prune_orphan_tmp(publish_dir)
        self._seq = next_generation_seq(publish_dir)
        #: Committed generations this publisher has flipped LATEST to.
        self.published = 0
        #: time.time() of the most recent LATEST flip (None before any).
        self.last_publish_time: Optional[float] = None

    def publish(self, vocab) -> str:
        """Publish the engine's current tables + the given (grown)
        vocabulary as the next generation; returns its name. The commit
        (rename + pointer flip) happens on the writer thread strictly
        after the matrix snapshot lands — call
        ``engine.wait_pending_saves()`` to barrier on it."""
        gen = generation_name(self._seq)
        self._seq += 1
        tmp = os.path.join(self.publish_dir, f"{gen}.tmp-{os.getpid()}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        words = list(vocab.words)
        table_version = self.engine.table_version

        def commit() -> None:
            self._commit(gen, tmp, words, table_version)

        self.engine.save_async(os.path.join(tmp, "matrix"), on_commit=commit)
        return gen

    def _commit(self, gen: str, tmp: str, words, table_version) -> None:
        """Writer-thread tail of one publish: metadata files into the
        temp dir, the atomic generation rename, the pointer flip, then
        retention. A crash anywhere leaves LATEST on the previous
        committed generation."""
        atomic_write_text(
            os.path.join(tmp, "words.txt"),
            "".join(w + "\n" for w in words),
        )
        atomic_write_json(
            os.path.join(tmp, "params.json"),
            json.loads(self.params.to_json()),
        )
        faults.fire("publish.pre_commit")
        final = os.path.join(self.publish_dir, gen)
        os.replace(tmp, final)
        self._fsync_dir(self.publish_dir)
        faults.fire("publish.pre_pointer")
        atomic_write_json(
            os.path.join(self.publish_dir, LATEST_NAME),
            {
                "generation": gen,
                "seq": int(gen.split("-")[1]),
                "published_unix": time.time(),
                "table_version": int(table_version),
                "vocab_size": len(words),
            },
        )
        self.published += 1
        self.last_publish_time = time.time()
        self._prune(keep_floor=gen)
        logger.info("published %s (%d words)", gen, len(words))

    @staticmethod
    def _fsync_dir(path: str) -> None:
        if os.environ.get("GLINT_CKPT_NO_FSYNC", "0") == "1":
            return
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _prune(self, keep_floor: str) -> None:
        """Drop committed generations older than the newest ``keep``
        (never the one just published — a replica may be staging the
        one before it, which the keep >= 2 floor protects)."""
        gens = sorted(
            (e for e in os.listdir(self.publish_dir) if _GEN_RE.match(e)),
            key=lambda e: int(_GEN_RE.match(e).group(1)),
        )
        for entry in gens[: max(0, len(gens) - self.keep)]:
            if entry == keep_floor:
                continue
            shutil.rmtree(
                os.path.join(self.publish_dir, entry), ignore_errors=True
            )
