"""The ``fit_stream`` loop: incremental ISGNS over an unbounded
sentence stream (arXiv:1704.03956), wired to the serving fleet through
the generation publish protocol.

Shape discipline is the whole design: the stream is consumed in bounded
**mini-epochs** through ONE fixed-capacity device buffer. Each round
fills the buffer host-side (counting via the online vocabulary,
subsampling with the current adaptive keep distribution), re-uploads it
with the real fill as the ``n_valid`` prefix bound, and drains it
through the packed pair scan — every round reuses the same compiled
programs because every traced shape (ids buffer, offsets buffer, pair
batch) is constant, exactly the fixed-shape batching insight
(arXiv:1611.06172) the batch engine already exploits. Likewise the
adaptive refreshes: ``set_noise_counts`` swaps alias-table VALUES at
fixed shapes, promotion widens the serving top-k mask through a traced
scalar, so a week-long trainer compiles in its first minute and never
again.

Differences from batch ``fit`` (all inherent to one-look streaming,
documented in README "Streaming training & hot-swap serving"):

- subsampling runs host-side while filling (the device compaction pass
  needs the whole static buffer; ``compact_corpus`` rejects an
  ``n_valid``-bounded view);
- the LR is constant at ``step_size`` by default (``anneal_words``
  restores a linear decay horizon) — an unbounded stream has no
  ``total_words`` to anneal against;
- promoted words join mid-run on spare extra rows with fresh init, and
  are never negative-sampled (the noise table spans the bootstrap
  vocabulary, like fastText bucket rows).
"""

from __future__ import annotations

import logging
import time
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from glint_word2vec_tpu.corpus.stream_vocab import (
    StreamVocab,
    bootstrap_stream_vocab,
)
from glint_word2vec_tpu.obs import start_run
from glint_word2vec_tpu.streaming.publish import SnapshotPublisher
from glint_word2vec_tpu.utils import faults
from glint_word2vec_tpu.utils.metrics import TrainingMetrics

logger = logging.getLogger(__name__)

#: LR denominator standing in for "unbounded": alpha stays within one
#: part in ~1e12 of step_size for any realistic stream.
_NO_ANNEAL_WORDS = 1 << 50


class StreamTrainer:
    """One long-lived streaming fit over a ``Word2Vec`` estimator's
    hyperparameters.

    Cadence knobs (all optional):

    - ``bootstrap_words``: stream prefix scanned batch-style (exact
      counts, frequency-ranked base vocabulary) before the engine is
      built. The bootstrap window itself is then trained first.
    - ``buffer_words`` / ``buffer_sentences``: the mini-epoch buffer
      capacity — the unit of training, accounting, and shape reuse.
    - ``extra_rows``: spare table rows reserved for online vocab
      growth (the promotion budget for the whole run).
    - ``refresh_words``: kept-word cadence for recomputing the
      adaptive noise + subsample distributions from live counts.
    - ``publish_seconds`` / ``publish_words``: generation publish
      cadence (whichever fires first); needs ``publish_dir``.
    - ``max_words`` / ``max_seconds``: optional stop bounds (smoke
      tests, bounded backfills); None = run until the stream ends.
    """

    def __init__(
        self,
        w2v,
        *,
        publish_dir: Optional[str] = None,
        bootstrap_words: int = 10_000,
        buffer_words: int = 65_536,
        buffer_sentences: Optional[int] = None,
        extra_rows: int = 1024,
        refresh_words: Optional[int] = None,
        publish_seconds: float = 30.0,
        publish_words: Optional[int] = None,
        publish_keep: int = 3,
        promote_min_count: Optional[int] = None,
        sketch_capacity: int = 65_536,
        anneal_words: Optional[int] = None,
        max_words: Optional[int] = None,
        max_seconds: Optional[float] = None,
    ):
        if buffer_words < 256:
            raise ValueError("buffer_words must be >= 256")
        if extra_rows < 0:
            raise ValueError("extra_rows must be >= 0")
        self.w2v = w2v
        self.publish_dir = publish_dir
        self.bootstrap_words = bootstrap_words
        self.buffer_words = buffer_words
        self.buffer_sentences = (
            buffer_sentences or max(16, buffer_words // 8)
        )
        self.extra_rows = extra_rows
        self.refresh_words = refresh_words or buffer_words
        self.publish_seconds = publish_seconds
        self.publish_words = publish_words
        self.publish_keep = publish_keep
        self.promote_min_count = promote_min_count
        self.sketch_capacity = sketch_capacity
        self.anneal_words = anneal_words
        self.max_words = max_words
        self.max_seconds = max_seconds
        # Run state, exposed for tests and the final metrics dict.
        self.engine = None
        self.vocab: Optional[StreamVocab] = None
        self.publisher: Optional[SnapshotPublisher] = None
        self.rounds = 0
        self.steps = 0
        self.words_trained = 0
        self.sentences_streamed = 0
        self.stream_lag_seconds = 0.0
        self.noise_drift_l1 = 0.0

    # -- stream plumbing -----------------------------------------------

    def _chunked(self, sentences: Iterable[Sequence[str]]) -> Iterator[List[str]]:
        """Sentences clipped to ``max_sentence_length`` pieces — the
        same chunking the batch paths apply at encode time. Empty
        sentences pass through unchanged: an idle follow-mode source
        yields ``[]`` heartbeats so the consumer can re-check its stop
        bounds and publish cadence instead of blocking forever."""
        msl = self.w2v.params.max_sentence_length
        for s in sentences:
            s = list(s)
            if len(s) <= msl:
                yield s
                continue
            for i in range(0, len(s), msl):
                piece = s[i : i + msl]
                if piece:
                    yield piece

    def _bootstrap(self, it: Iterator[List[str]], t_start: float) -> List[List[str]]:
        """Pull the bootstrap window off the stream: enough sentences
        to cover ``bootstrap_words`` raw words (or the whole stream —
        or the ``max_seconds`` budget — if either ends first)."""
        window: List[List[str]] = []
        seen = 0
        for s in it:
            if not s:
                # idle heartbeat: a quiet follow-mode stream must not
                # pin a bounded run inside the bootstrap
                if (
                    self.max_seconds
                    and time.time() - t_start >= self.max_seconds
                ):
                    break
                continue
            window.append(s)
            seen += len(s)
            if seen >= self.bootstrap_words:
                break
        if not window:
            raise ValueError("empty stream: nothing to bootstrap from")
        return window

    # -- engine construction -------------------------------------------

    def _make_engine(self, mesh):
        from glint_word2vec_tpu.parallel.engine import EmbeddingEngine

        p = self.w2v.params
        return EmbeddingEngine(
            mesh,
            self.vocab.base_size,
            p.vector_size,
            self.vocab.noise_counts(),
            num_negatives=p.num_negatives,
            unigram_power=p.unigram_power,
            unigram_table_size=p.unigram_table_size,
            seed=p.seed,
            dtype=p.dtype,
            extra_rows=self.extra_rows,
            shared_negatives=p.shared_negatives,
            compute_dtype=p.compute_dtype,
            layout=p.layout,
        )

    # -- the loop -------------------------------------------------------

    def run(self, sentences: Iterable[Sequence[str]]):
        """Consume the stream; returns the final fitted
        ``Word2VecModel`` (grown vocabulary included) when the stream
        ends or a stop bound trips."""
        import jax

        from glint_word2vec_tpu.corpus.batching import packed_pair_batch
        from glint_word2vec_tpu.models.word2vec import (
            Word2VecModel,
            _ckpt_wait_timeout,
        )

        p = self.w2v.params
        if self.buffer_words < p.max_sentence_length:
            # _chunked clips every sentence to max_sentence_length
            # pieces; a piece that can never fit the buffer would spin
            # the carry loop forever.
            raise ValueError(
                f"buffer_words ({self.buffer_words}) must be >= "
                f"max_sentence_length ({p.max_sentence_length}) so "
                "every sentence piece fits the mini-epoch buffer"
            )
        t_start = time.time()
        it = self._chunked(sentences)
        window = self._bootstrap(it, t_start)
        self.vocab = bootstrap_stream_vocab(
            window,
            min_count=p.min_count,
            sketch_capacity=self.sketch_capacity,
            max_size=None,
        )
        sv = self.vocab
        mesh = self.w2v._make_mesh()
        if p.batch_size % mesh.shape["data"]:
            raise ValueError(
                f"batch_size ({p.batch_size}) must be divisible by the "
                f"data-axis size ({mesh.shape['data']})"
            )
        engine = self.engine = self._make_engine(mesh)
        logger.info(
            "stream bootstrap: %d words vocab, %d spare rows, "
            "buffer %d words",
            sv.base_size, self.extra_rows, self.buffer_words,
        )
        if self.publish_dir:
            self.publisher = SnapshotPublisher(
                self.publish_dir, engine, p, keep=self.publish_keep,
            )
        obs_run = start_run(
            self.w2v.obs, pipeline="stream", total_epochs=0,
            total_words=0, engine=engine,
        )
        metrics = TrainingMetrics()
        obs_run.attach_metrics(metrics)
        min_count = (
            self.promote_min_count
            if self.promote_min_count is not None else p.min_count
        )
        total_words = (
            self.anneal_words + 1
            if self.anneal_words else _NO_ANNEAL_WORDS
        )
        B, W, spc = p.batch_size, p.window, p.steps_per_call
        # ~B positions per packed step (grid-equivalent synchronous
        # batch — see corpus/batching.packed_pair_batch).
        pair_batch = packed_pair_batch(B, W, mesh.shape["data"])
        base_key = jax.random.PRNGKey(p.seed)
        keep = sv.keep_probabilities(p.subsample_ratio)
        rng = np.random.default_rng(p.seed)
        prev_noise = sv.noise_weights(p.unigram_power)
        words_at_refresh = 0
        words_at_publish = 0
        last_publish_t = time.time()

        def publish_now(fill_gauge: int) -> None:
            nonlocal last_publish_t, words_at_publish
            with obs_run.span("publish", round=self.rounds):
                self.publisher.publish(sv.snapshot_vocabulary())
            last_publish_t = time.time()
            words_at_publish = self.words_trained
            self._update_stream_gauges(obs_run, fill_gauge)

        # The bootstrap window is the first training data: replay it
        # through the same buffer path the live stream uses.
        import itertools

        stream = itertools.chain(window, it)
        # The bootstrap window's occurrences are already in the counts
        # (exact, from bootstrap_stream_vocab) — replay it encode-only
        # so nothing is counted twice.
        bootstrap_left = len(window)
        carry: Optional[List[int]] = None
        exhausted = False
        try:
            while not exhausted:
                if self.max_words and self.words_trained >= self.max_words:
                    break
                if (
                    self.max_seconds
                    and time.time() - t_start >= self.max_seconds
                ):
                    break
                # -- fill one mini-epoch buffer host-side --------------
                t_fill0 = time.time()
                ids_buf = np.zeros(self.buffer_words, np.int32)
                offsets = [0]
                fill = 0
                with obs_run.span("stream_fill", round=self.rounds):
                    while (
                        fill < self.buffer_words
                        and len(offsets) <= self.buffer_sentences
                    ):
                        # A slow or idle stream must not starve the
                        # bounds or the publish cadence: re-check them
                        # between pulls (the source yields [] heartbeats
                        # while idle), training whatever partial buffer
                        # is on hand when a deadline fires.
                        if (
                            self.max_seconds
                            and time.time() - t_start >= self.max_seconds
                        ):
                            break
                        if (
                            self.publisher is not None
                            and time.time() - last_publish_t
                            >= self.publish_seconds
                            and (
                                fill
                                or self.words_trained > words_at_publish
                            )
                        ):
                            # fill > 0: train the partial buffer so the
                            # due publish carries it. fill == 0 with
                            # unpublished words: break so the idle
                            # branch below can publish — an UNBOUNDED
                            # run would otherwise spin here on
                            # heartbeats and starve the cadence.
                            break
                        if carry is not None:
                            # Stashed AFTER last round's subsample pass:
                            # running it through the keep draw again
                            # would thin its frequent words to p^2.
                            enc, carry = carry, None
                            from_carry = True
                        else:
                            from_carry = False
                            sent = next(stream, None)
                            if sent is None:
                                exhausted = True
                                break
                            if not sent:
                                # idle-stream heartbeat: nothing to
                                # count, just re-check the bounds above
                                continue
                            # Count + encode through the online vocab
                            # (OOV feeds the candidate sketch), then
                            # subsample with the live keep distribution.
                            if bootstrap_left > 0:
                                bootstrap_left -= 1
                                enc = sv.encode(sent)
                            else:
                                enc = sv.observe(sent)
                            self.sentences_streamed += 1
                        if not enc:
                            continue
                        if p.subsample_ratio > 0 and not from_carry:
                            # graftlint: ignore[sync-point] enc is a host id list from the online vocab
                            arr = np.asarray(enc, np.int32)
                            enc = arr[
                                rng.random(arr.shape[0]) < keep[arr]
                            ].tolist()
                            if not enc:
                                continue
                        if fill + len(enc) > self.buffer_words:
                            carry = enc
                            break
                        ids_buf[fill : fill + len(enc)] = enc
                        fill += len(enc)
                        offsets.append(fill)
                if fill == 0:
                    if exhausted:
                        break
                    # An idle stream must not starve the publish
                    # cadence: rounds trained since the last publish
                    # still reach the fleet while no new data arrives.
                    if (
                        self.publisher is not None
                        and self.words_trained > words_at_publish
                        and time.time() - last_publish_t
                        >= self.publish_seconds
                    ):
                        publish_now(0)
                    continue
                # -- grow: promote candidates onto spare rows ----------
                promoted_round = 0
                while engine.extra_rows_free > 0:
                    cands = sv.promotable(
                        min_count, limit=engine.extra_rows_free
                    )
                    if not cands:
                        break
                    # One batched mutation per burst: a vocabulary
                    # shift can promote many words at once, and
                    # per-word writes would serialize tiny dispatches.
                    rows = engine.assign_extra_rows(
                        [word for word, _ in cands]
                    )
                    for row, (word, est) in zip(rows, cands):
                        idx = sv.promote(word, est)
                        if row != idx:  # pragma: no cover - invariant
                            raise AssertionError(
                                f"row/vocab drift: engine row {row} != "
                                f"vocab index {idx} for {word!r}"
                            )
                    promoted_round += len(cands)
                # -- adapt: refresh noise + subsample distributions ----
                if (
                    promoted_round
                    or sv.train_words_count - words_at_refresh
                    >= self.refresh_words
                ):
                    words_at_refresh = sv.train_words_count
                    engine.set_noise_counts(sv.noise_counts())
                    keep = sv.keep_probabilities(p.subsample_ratio)
                    cur = sv.noise_weights(p.unigram_power)
                    # graftlint: ignore[sync-point] both operands are host numpy distributions
                    self.noise_drift_l1 = float(
                        np.abs(cur - prev_noise).sum()
                    )
                    prev_noise = cur
                # -- train: one bounded mini-epoch ---------------------
                # +2: up to buffer_sentences real boundaries after the
                # leading 0, plus the dedicated final pad boundary — a
                # full sentence buffer must not have its last real
                # boundary overwritten by the pad one.
                offsets_arr = np.full(
                    self.buffer_sentences + 2, fill, np.int64
                )
                offsets_arr[: len(offsets)] = offsets
                # The trailing pad run is its own "sentence": centers in
                # it sit at/past n_valid (zero-mask lanes), and no real
                # sentence window can cross into it.
                offsets_arr[-1] = self.buffer_words
                with obs_run.span("upload_corpus", words=fill):
                    engine.upload_corpus(ids_buf, offsets_arr, n_valid=fill)
                pos = 0
                while pos < fill:
                    faults.fire("worker.step")
                    with metrics.timing("step"), obs_run.span(
                        "device_steps", step0=self.steps, n=spc, packed=True
                    ):
                        losses, pair_counts, pos_ends, alphas = (
                            engine.train_steps_corpus_packed(
                                pos, pair_batch, W, B, base_key, spc,
                                step0=self.steps, grid_step0=self.steps,
                                step_size=p.step_size,
                                total_words=total_words,
                                words_base=self.words_trained,
                            )
                        )
                    pos = self._harvest(
                        metrics, obs_run, losses, pos_ends, alphas,
                        pos, fill,
                    )
                self.words_trained += fill
                self.rounds += 1
                self.stream_lag_seconds = time.time() - t_fill0
                obs_run.update(
                    epoch=self.rounds, step=self.steps,
                    words_done=self.words_trained,
                )
                self._update_stream_gauges(obs_run, fill)
                # -- publish on cadence --------------------------------
                if self.publisher is not None:
                    due_t = (
                        time.time() - last_publish_t
                        >= self.publish_seconds
                    )
                    due_w = (
                        self.publish_words is not None
                        and self.words_trained - words_at_publish
                        >= self.publish_words
                    )
                    if due_t or due_w:
                        publish_now(fill)
            # Final publish: the stream's last words must reach the
            # fleet even when the cadence did not fire.
            if self.publisher is not None and self.words_trained:
                self.publisher.publish(sv.snapshot_vocabulary())
            engine.wait_pending_saves(timeout=_ckpt_wait_timeout())
            self._update_stream_gauges(obs_run, 0)
        except BaseException:
            engine.wait_pending_saves(
                reraise=False, timeout=_ckpt_wait_timeout()
            )
            obs_run.close(failed=True)
            raise
        finally:
            obs_run.close()
        logger.info(
            "stream done: %d rounds, %d words trained, %d promoted, "
            "%d generations",
            self.rounds, self.words_trained, sv.promoted,
            self.publisher.published if self.publisher else 0,
        )
        model = Word2VecModel(sv.snapshot_vocabulary(), engine, p)
        model.training_metrics = {
            **metrics.summary(),
            "pipeline": "stream",
            # The stream drains through the packed pair scan, so it
            # rides the fused Pallas megakernel whenever the engine does.
            "pallas_fused": bool(getattr(engine, "_pallas_fused", False)),
            "rounds": self.rounds,
            "words_trained": self.words_trained,
            "vocab_size": sv.size,
            "promoted_words": sv.promoted,
            "oov_words_seen": sv.oov_words_seen,
            "generations_published": (
                self.publisher.published if self.publisher else 0
            ),
        }
        return model

    def _harvest(self, metrics, obs_run, losses, pos_ends, alphas,
                 start: int, n_valid: int) -> int:
        """Sync one dispatched group's result scalars into the metrics
        and return the consumed position. The streaming loop harvests
        synchronously — its host work between groups (nothing: the
        buffer is already uploaded) cannot starve the device the way
        the batch fit loop's could."""
        with metrics.timing("step"), obs_run.span(
            "readback_harvest", packed=True
        ) as span:
            pos_ends_h = np.asarray(pos_ends)
            losses_h = np.asarray(losses)
            alphas_h = np.asarray(alphas)
            starts = np.concatenate(([start], pos_ends_h[:-1]))
            n_real = int((starts < n_valid).sum())
            span.update(n=n_real)
            for i in range(n_real):
                self.steps += 1
                metrics.record_step(
                    self.words_trained + int(min(pos_ends_h[i], n_valid)),
                    loss=losses_h[i], alpha=float(alphas_h[i]),
                )
            obs_run.observe_losses(
                self.steps - n_real, losses_h, n_real
            )
            self.steps += losses_h.shape[0] - n_real  # tail keys consumed
        return int(pos_ends_h[-1])

    def _update_stream_gauges(self, obs_run, fill: int) -> None:
        sv, engine = self.vocab, self.engine
        pub = self.publisher
        obs_run.update_streaming(
            words_streamed=sv.train_words_count,
            sentences_streamed=self.sentences_streamed,
            oov_words=sv.oov_words_seen,
            vocab_size=sv.size,
            promoted_words=sv.promoted,
            extra_rows_free=engine.extra_rows_free,
            sketch_fill=len(sv.sketch) / max(sv.sketch.capacity, 1),
            noise_drift_l1=self.noise_drift_l1,
            stream_lag_seconds=self.stream_lag_seconds,
            generations_published=pub.published if pub else 0,
            last_publish_unix=pub.last_publish_time if pub else None,
            buffer_fill=fill / max(self.buffer_words, 1),
        )
