"""Streaming ISGNS (ISSUE 10): incremental training on an unbounded
sentence stream, publishing model generations a serving fleet hot-swaps
under live traffic.

Three pieces close the trainer->server loop:

- :mod:`glint_word2vec_tpu.corpus.stream_vocab` — the online vocabulary
  (exact live counts + space-saving candidate sketch + promotion onto
  the engine's spare extra rows, arXiv:1704.03956).
- :mod:`glint_word2vec_tpu.streaming.publish` — the generation commit
  protocol: ``gen-NNNNNN`` model directories committed by one atomic
  rename, referenced by an atomically-flipped ``LATEST.json`` pointer,
  so a watcher can never observe a partial snapshot.
- :mod:`glint_word2vec_tpu.streaming.trainer` — the long-lived
  ``fit_stream`` loop: bounded mini-epochs through the engine's packed
  device-corpus path, adaptive distribution refresh, online vocab
  growth, cadence publishing, and stream gauges on the obs stack.

The serving half (snapshot watcher + ``/reload`` + the drained atomic
table flip) lives in :mod:`glint_word2vec_tpu.serving`.
"""

from glint_word2vec_tpu.streaming.publish import (
    LATEST_NAME,
    SnapshotPublisher,
    generation_name,
    next_generation_seq,
    read_latest,
    resolve_latest,
)
from glint_word2vec_tpu.streaming.trainer import StreamTrainer

__all__ = [
    "LATEST_NAME",
    "SnapshotPublisher",
    "StreamTrainer",
    "generation_name",
    "next_generation_seq",
    "read_latest",
    "resolve_latest",
]
