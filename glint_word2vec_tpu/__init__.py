"""glint_word2vec_tpu — a TPU-native framework for very-large-vocabulary word2vec.

A ground-up reimplementation of the capabilities of MGabr/glint-word2vec
(Spark + Glint parameter servers, skip-gram with negative sampling) designed
for TPUs: the parameter-server-sharded ``BigWord2VecMatrix`` with its
server-side ``dotprod``/``adjust`` RPCs becomes a vocab-row-sharded embedding
table living in TPU HBM, updated by a single jit-compiled SGNS step under
``shard_map``; Akka push/pull RPCs become XLA collectives over ICI; the Spark
sentence RDD becomes a vectorized host batching pipeline feeding the device.

Public API (capability map to the reference, see SURVEY.md §2):

- :class:`Word2Vec` — the estimator (reference: ``ServerSideGlintWord2Vec``,
  ml/feature/ServerSideGlintWord2Vec.scala:228 and
  mllib/feature/ServerSideGlintWord2Vec.scala:65).
- :class:`Word2VecModel` — the fitted model: transform / find_synonyms /
  analogy / get_vectors / to_local / save / load (reference:
  ``ServerSideGlintWord2VecModel``, mllib:460-726, ml:319-600).
- :class:`glint_word2vec_tpu.parallel.engine.EmbeddingEngine` — the sharded
  matrix engine replacing the Glint parameter-server client
  (``BigWord2VecMatrix`` ops pull / pullAverage / norms / multiply /
  dotprod+adjust, SURVEY.md §2.2).
- :mod:`glint_word2vec_tpu.corpus` — vocab build, subsampling, windowing,
  unigram alias tables (reference: ``learnVocab`` and the ``doFit`` data
  passes, mllib:258-390).
"""

from glint_word2vec_tpu.version import __version__

__all__ = [
    "__version__",
    "Word2Vec",
    "Word2VecModel",
    "LocalWord2VecModel",
    "Word2VecParams",
    "FastTextWord2Vec",
    "FastTextModel",
    "FastTextParams",
    "load_model",
    "ServerSideGlintWord2Vec",
    "ServerSideGlintWord2VecModel",
    "ObsConfig",
    "TrainingDiverged",
]


def __getattr__(name):
    # Lazy so that host-only use (corpus tooling) never imports jax.
    if name in ("Word2Vec", "Word2VecModel", "LocalWord2VecModel"):
        from glint_word2vec_tpu.models import word2vec

        return getattr(word2vec, name)
    if name in ("FastTextWord2Vec", "FastTextModel", "FastTextParams"):
        from glint_word2vec_tpu.models import fasttext

        return getattr(fasttext, name)
    if name == "load_model":
        from glint_word2vec_tpu.models import load_model

        return load_model
    if name == "Word2VecParams":
        from glint_word2vec_tpu.utils.params import Word2VecParams

        return Word2VecParams
    if name in ("ObsConfig", "TrainingDiverged"):
        # Run-wide observability (obs/): heartbeat, event log, canary.
        from glint_word2vec_tpu import obs

        return getattr(obs, name)
    if name in ("ServerSideGlintWord2Vec", "ServerSideGlintWord2VecModel"):
        # Reference-surface compatibility layer (compat.py): the PySpark
        # binding API re-exposed over this framework.
        from glint_word2vec_tpu import compat

        return getattr(compat, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
