"""Benchmark: sustained SGNS training throughput on the available device.

Measures the fused train step (the dotprod+adjust equivalent of the
reference's hot loop, mllib/feature/ServerSideGlintWord2Vec.scala:421-425)
in steady state on a realistic large-vocab configuration, reporting trained
words per second per chip plus an MFU estimate. Baseline: the driver
north-star of 50M words/sec on a v5e-32 (BASELINE.json) = 1.5625M
words/sec/chip; the reference itself publishes no throughput numbers
(BASELINE.md).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "words/sec/chip", "vs_baseline": N, ...}

The headline "value" is the PER-PAIR estimator (reference semantics: n fresh
negatives per (center, context) pair) so vs_baseline is comparable to the
reference's algorithm; the shared-negative-pool mode (the TPU-shaped
estimator) is reported alongside under "modes". The full config is echoed in
the line so no number is ever ambiguous about what it measured.

Robustness: the actual measurement runs in a worker subprocess. If TPU
backend init fails or hangs (the tunnel is flaky: round 1 died with
UNAVAILABLE at import), the orchestrator retries once, then falls back to
CPU with the platform recorded, then — only if even that fails — emits a
diagnostic JSON line. It always exits 0 with one JSON line on stdout.

Every mode reports ``pairs_per_sec`` (measured useful (center, context)
pairs trained per second) and ``effective_words_per_sec`` :=
``pairs_per_sec / context_lanes`` — useful-pair throughput in dense-word
units, the number on which grid-vs-packed dispatch shapes are directly
comparable. (The naive ``words_per_sec / mask_density`` form would
INFLATE a mode by its own masked-lane waste — a grid cell at density
0.43 would score 2.3x its real training rate — so the pair-normalized
form is what the packed-vs-grid gate in BENCH_PACKED.json uses.)

Environment knobs:
  BENCH_VOCAB, BENCH_DIM, BENCH_BATCH, BENCH_SPC (minibatches per device
  dispatch = scan length), BENCH_SHARED_NEG (pool size for the shared mode),
  BENCH_MODES (default
  "per_pair,per_pair_bf16t,per_pair_bf16ct,shared_bf16t,shared_bf16ct,corpus,corpus_subsample,corpus_packed"
  — the `_bf16t` cells are the PROPER mixed-precision regime (bf16
  STORAGE, fp32 compute/accumulate — the fused-kernel target geometry,
  ISSUE 11), distinct from `_bf16ct` which also runs bf16 MXU operands;
  "corpus" is the production fit/fit_file path with minibatches assembled
  on device from the uploaded corpus; "corpus_subsample" is the same path
  with frequency subsampling on (ratio BENCH_SUBSAMPLE, default 1e-3):
  a per-epoch on-device compaction pass, then training over the
  compacted stream — the realistic production config; "corpus_packed" is
  the corpus path under dense pair packing (set_batch_packing("dense"),
  ISSUE 4): valid pairs prefix-sum-compacted into dense pair batches of
  batch*context_lanes slots, reported with packed fill as its
  mask_density; suffixes:
  "_bf16c" = bf16 MXU operands with f32 accumulation, "_bf16t" = bf16
  TABLES for that mode (overriding BENCH_DTYPE; halves gather/scatter
  bytes), "_bf16ct" = both; "stall_overlap" (not in the default set) is
  the ISSUE-5 checkpoint-pause cell — words/sec with checkpointing every
  N groups, blocking vs async saves, gating >= 80% pause removal,
  recorded in BENCH_STALL.json), BENCH_DTYPE (run-level table dtype, default
  float32 so the suffixless per_pair headline stays comparable across
  rounds; each mode's effective table dtype is echoed in its results),
  BENCH_PLATFORM (force a JAX platform), BENCH_ATTEMPT_TIMEOUT (seconds per
  worker attempt, default 600; the retry attempt is capped at 300),
  BENCH_MIN_SECONDS (timed-loop floor), BENCH_HOST_INPUTS=1 (feed numpy
  batches per dispatch instead of device-resident arrays — a diagnostic for
  the host->device transfer cost the production device-resident corpus
  pipeline avoids).
"""

import json
import os
import subprocess
import sys
import time

BASELINE_WORDS_PER_SEC_PER_CHIP = 50e6 / 32

# Peak dense-matmul throughput by device kind, used only for the MFU
# *estimate*. Values are the published bf16 peaks (the 394 TFLOPS
# previously listed for v5e was the int8 peak — round-3 ADVICE.md). For
# modes whose contractions run f32 operands, the MXU needs multiple bf16
# passes; we charge those against bf16_peak/2 and record the assumption.
_PEAK_FLOPS_BF16 = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_for(device_kind: str, compute_dtype: str):
    dk = device_kind.lower()
    for tag, peak in _PEAK_FLOPS_BF16:
        if tag in dk:
            return peak if compute_dtype == "bfloat16" else peak / 2
    return None


def _config_from_env():
    return {
        "vocab": int(os.environ.get("BENCH_VOCAB", 1_000_000)),
        "dim": int(os.environ.get("BENCH_DIM", 300)),
        "batch": int(os.environ.get("BENCH_BATCH", 8192)),
        "steps_per_call": int(os.environ.get("BENCH_SPC", 32)),
        "shared_negatives": int(os.environ.get("BENCH_SHARED_NEG", 4096)),
        "subsample_ratio": float(os.environ.get("BENCH_SUBSAMPLE", 1e-3)),
        "negatives": 5,
        "context_lanes": 7,
        # Table dtype defaults to float32 so the per_pair headline stays
        # directly comparable with BENCH_r03 (isolating the exchange
        # rework); the bf16-table geometry is swept by
        # scripts/bench_sweep.py, which sets BENCH_DTYPE explicitly.
        "dtype": os.environ.get("BENCH_DTYPE", "float32"),
        # Mode suffixes: _bf16c = bf16 MXU operands, _bf16t = bf16 tables,
        # _bf16ct = both; no suffix = f32 (exactness-tested numerics).
        # Estimators: per_pair (reference semantics, pre-built batches),
        # shared (pool estimator), corpus (the PRODUCTION fit/fit_file
        # path: minibatch windows assembled ON DEVICE from the uploaded
        # corpus — includes the window-assembly cost the other modes
        # skip), corpus_subsample (corpus + the per-epoch on-device
        # subsample-compact pass — the realistic production config).
        # Defaults: the r03-comparable headline + the per-pair fast path
        # + the fastest estimator config + both production paths.
        # The _bf16t cells are the mixed-precision surface ISSUE 11
        # cares about: bf16 STORAGE with fp32 compute/accumulation (the
        # fused-kernel regime). _bf16ct additionally runs the MXU
        # contractions on bf16 operands. Both ride _mode_parts.
        "modes": os.environ.get(
            "BENCH_MODES",
            "per_pair,per_pair_bf16t,per_pair_bf16ct,shared_bf16t,"
            "shared_bf16ct,corpus,corpus_subsample,corpus_packed",
        ),
    }


def _flops_per_step(mode: str, cfg, mask_density: float) -> float:
    """USEFUL FLOPs of one minibatch update (matmul-equivalent count).

    Per-pair (ops/sgns.py sgns_grads + rank-1 expansion): f_pos 2BCd,
    f_neg 2BCnd, d_center 2BCd+2BCnd, outer products BCd+BCnd, scatter adds
    BCd+BCnd+Bd  => ~6BCd(1+n) + Bd.
    Shared pool (shared_sgns_grads): f_pos 2BCd, f_pool 2BSd, d_center
    2BCd+2BSd, d_pool 2BSd, outer+scatter 2BCd+Bd+Sd => ~6BCd + 6BSd.

    Every context-lane term is scaled by the mode's MEASURED mask
    density: the MXU executes all C static lanes either way, but masked
    lanes do no useful work, so crediting them would inflate MFU — and
    inflate it unevenly (synthetic masks run ~0.85 dense, the corpus
    mode's shrunk windows ~0.42; round-4 verdict weak #8). The MFU
    reported is therefore useful-work MFU on a consistent basis.
    """
    B, C, d, n = cfg["batch"], cfg["context_lanes"], cfg["dim"], cfg["negatives"]
    estimator, _, _ = _mode_parts(mode)
    if estimator in ("per_pair", "corpus", "corpus_subsample"):
        return 6.0 * B * C * d * (1 + n) * mask_density + B * d
    S = cfg["shared_negatives"]
    return 6.0 * B * C * d * mask_density + 6.0 * B * S * d + B * d + S * d


# ----------------------------------------------------------------------
# Worker: does the measurement, prints the JSON line.
# ----------------------------------------------------------------------


def _bench_obs_overhead(jax, np):
    """ISSUE 3 overhead guard, re-run for ISSUE 8 with the step-time
    attribution ledger in the stack: a fit with the full observability
    suite enabled (event ring + JSONL sink + Chrome-trace export +
    heartbeat server + status file + warn canary + attribution ledger
    with STEPTIME.json dump) must stay within 3% words/sec of the same
    fit with observability off (where the ledger path is the one
    module-global NULL_SPAN read). Runs the real production fit
    (device-resident corpus path) three times — warm-up (compiles,
    discarded), baseline, instrumented — and reports both throughputs,
    the overhead fraction, and the ledger's phase breakdown. Mode name:
    ``obs_overhead`` in BENCH_MODES (not in the default set; words/sec
    here is from a small fit, not comparable to the engine-loop
    modes)."""
    import tempfile

    from glint_word2vec_tpu.models.word2vec import Word2Vec
    from glint_word2vec_tpu.obs import ObsConfig
    from glint_word2vec_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    n_words = int(os.environ.get("BENCH_OBS_WORDS", 400_000))
    vocab = [f"w{i}" for i in range(2000)]
    sent_len = 20
    sentences = [
        [vocab[j] for j in rng.integers(0, len(vocab), sent_len)]
        for _ in range(n_words // sent_len)
    ]

    def run(obs):
        model = Word2Vec(
            mesh=make_mesh(1, 1), obs=obs, vector_size=64, min_count=1,
            batch_size=1024, num_iterations=2, seed=1, steps_per_call=8,
        ).fit(sentences)
        wps = model.training_metrics["words_per_sec"]
        pipeline = model.training_metrics["pipeline"]
        model.stop()
        return wps, pipeline

    run(None)  # compile warm-up fit, discarded
    base, pipeline = run(None)
    with tempfile.TemporaryDirectory() as td:
        obs = ObsConfig(
            event_log=os.path.join(td, "events.jsonl"),
            chrome_trace=os.path.join(td, "trace.json"),
            status_port=0,
            status_file=os.path.join(td, "status.json"),
            canary="warn",
            steptime_path=os.path.join(td, "STEPTIME.json"),
        )
        instrumented, _ = run(obs)
        import json as _json

        with open(os.path.join(td, "STEPTIME.json")) as f:
            steptime = _json.load(f)
    return {
        "words_per_sec": instrumented,
        "words_per_sec_baseline": base,
        "overhead_frac": round(1.0 - instrumented / base, 4),
        "corpus_words": n_words,
        "pipeline": pipeline,
        "steptime_wall_seconds": steptime["wall_seconds"],
        "steptime_phases": {
            p: info["seconds"]
            for p, info in steptime["phases"].items()
        },
        "inputs": "fit_list",
    }


def _bench_stall_overlap(jax, np):
    """ISSUE 5 acceptance cell: words/sec with checkpointing every N
    dispatch groups, blocking vs async saves, over the device-resident
    corpus scan. The gated quantity is the PER-CHECKPOINT WALL-CLOCK
    PAUSE at the fit loop's call site — the time the dispatching thread
    is blocked per save, which is exactly the device-pipeline bubble a
    checkpoint used to cost. Async saves must remove >= 80% of it
    (``ckpt_pause_removed_frac``). Words/sec under each regime is
    reported alongside but NOT gated: on a CPU container the "device"
    and the writer thread share the same cores, so end-to-end throughput
    under async saves also pays the write's CPU time — on a real TPU the
    chip keeps training through it. Mode name ``stall_overlap`` in
    BENCH_MODES (not in the default set); recorded in BENCH_STALL.json.

    Knobs: BENCH_STALL_VOCAB/DIM/BATCH/SPC (table + dispatch geometry),
    BENCH_STALL_GROUPS (timed dispatch groups), BENCH_STALL_CKPT_EVERY
    (groups between checkpoints)."""
    import shutil
    import tempfile

    from glint_word2vec_tpu.parallel.engine import EmbeddingEngine
    from glint_word2vec_tpu.parallel.mesh import make_mesh

    V = int(os.environ.get("BENCH_STALL_VOCAB", 200_000))
    d = int(os.environ.get("BENCH_STALL_DIM", 128))
    B = int(os.environ.get("BENCH_STALL_BATCH", 2048))
    spc = int(os.environ.get("BENCH_STALL_SPC", 8))
    groups = int(os.environ.get("BENCH_STALL_GROUPS", 24))
    every = int(os.environ.get("BENCH_STALL_CKPT_EVERY", 4))
    if every <= 0 or groups < every:
        raise ValueError(
            f"BENCH_STALL_CKPT_EVERY={every} must be in [1, "
            f"BENCH_STALL_GROUPS={groups}] or no checkpoint ever fires "
            "and there is no pause to measure"
        )
    W = 5
    ranks = np.arange(1, V + 1, dtype=np.float64)
    counts = np.maximum((1e9 / ranks), 1.0).astype(np.int64)
    p = counts / counts.sum()
    rng = np.random.default_rng(0)
    sent_len = 40
    N = max(2 * spc * B, 1_000_000)
    N -= N % sent_len
    ids = rng.choice(V, size=N, p=p).astype(np.int32)
    offsets = np.arange(0, N + sent_len, sent_len, dtype=np.int64)
    mesh = make_mesh(1, 1, devices=[jax.devices()[0]])
    alphas = np.full(spc, 0.025, np.float32)
    key = jax.random.PRNGKey(0)
    span = max(N - spc * B, 1)

    def run(async_mode: bool):
        eng = EmbeddingEngine(mesh, V, d, counts, seed=0)
        eng.upload_corpus(ids, offsets)
        td = tempfile.mkdtemp(prefix="stall_bench_")
        # Warm every compile (train scan, snapshot copy) and the page
        # cache before timing.
        jax.block_until_ready(
            eng.train_steps_corpus(0, B, W, key, alphas, 0)
        )
        if async_mode:
            eng.save_async(os.path.join(td, "warm"))
            eng.wait_pending_saves()
        else:
            eng.save(os.path.join(td, "warm"))
        pauses = []
        t_start = time.time()
        last = None
        for g in range(groups):
            start = (g * spc * B) % span
            last = eng.train_steps_corpus(start, B, W, key, alphas,
                                          g * spc)
            if (g + 1) % every == 0:
                ck = os.path.join(td, f"ckpt-{g}")
                t0 = time.time()
                if async_mode:
                    eng.save_async(ck)
                else:
                    eng.save(ck)
                pauses.append(time.time() - t0)
        eng.wait_pending_saves()
        jax.block_until_ready(last)
        wall = time.time() - t_start
        stats = eng.checkpoint_stats()
        eng.destroy()
        shutil.rmtree(td, ignore_errors=True)
        wps = groups * spc * B / wall
        return wps, pauses, stats

    sync_wps, sync_pauses, _ = run(False)
    async_wps, async_pauses, async_stats = run(True)
    mean = lambda xs: sum(xs) / max(len(xs), 1)  # noqa: E731
    removed = 1.0 - mean(async_pauses) / max(mean(sync_pauses), 1e-12)
    return {
        "words_per_sec": round(async_wps, 1),
        "words_per_sec_sync_ckpt": round(sync_wps, 1),
        "ckpt_pause_sync_ms": round(mean(sync_pauses) * 1e3, 3),
        "ckpt_pause_sync_max_ms": round(max(sync_pauses) * 1e3, 3),
        "ckpt_pause_async_ms": round(mean(async_pauses) * 1e3, 3),
        "ckpt_pause_async_max_ms": round(max(async_pauses) * 1e3, 3),
        "ckpt_pause_removed_frac": round(removed, 4),
        "gate_pause_removed_min": 0.8,
        "gate_pass": bool(removed >= 0.8),
        "checkpoints_per_run": len(sync_pauses),
        "ckpt_every_groups": every,
        "async_save_waits": async_stats.get("async_save_waits"),
        "vocab": V, "dim": d, "batch": B, "steps_per_call": spc,
        "timed_groups": groups, "window": W,
        "corpus_words_device": int(N),
        "table_bytes_per_copy": int(2 * V * d * 4),
        "inputs": "device_corpus",
        "caveats": (
            "CPU container: the writer thread competes with the XLA "
            "'device' for the same cores, so end-to-end words/sec under "
            "async saves still pays the write's CPU time (a real "
            "accelerator trains through it); single-run wall-clock "
            "numbers are subject to container CPU contention. The gated "
            "pause is the call-site blocking time of the identical "
            "snapshot geometry in both modes."
        ),
    }


def _mode_parts(mode: str):
    """Split a mode name into (estimator, compute_dtype, table_dtype).

    Suffixes: "_bf16c" = bf16 MXU operands; "_bf16t" = bf16 tables
    (halves gather/scatter HBM bytes); "_bf16ct" = both. No suffix = f32
    everywhere (the exactness-tested reference numerics). table_dtype is
    None when the mode doesn't override the run-level BENCH_DTYPE.
    """
    for suf, cd, td in (
        ("_bf16ct", "bfloat16", "bfloat16"),
        ("_bf16c", "bfloat16", None),
        ("_bf16t", "float32", "bfloat16"),
    ):
        if mode.endswith(suf):
            return mode[: -len(suf)], cd, td
    return mode, "float32", None


def _bench_mode(jax, mesh, cfg, mode: str, np):
    from glint_word2vec_tpu.parallel.engine import EmbeddingEngine

    V, d, B = cfg["vocab"], cfg["dim"], cfg["batch"]
    spc, C, n = cfg["steps_per_call"], cfg["context_lanes"], cfg["negatives"]
    estimator, compute_dtype, table_dtype = _mode_parts(mode)
    if estimator == "obs_overhead":
        return _bench_obs_overhead(jax, np)
    if estimator == "stall_overlap":
        return _bench_stall_overlap(jax, np)
    shared = cfg["shared_negatives"] if estimator == "shared" else 0

    # Zipf-ish counts: realistic index skew for gathers and the noise table.
    ranks = np.arange(1, V + 1, dtype=np.float64)
    counts = np.maximum((1e9 / ranks), 1.0).astype(np.int64)

    eng = EmbeddingEngine(
        mesh, V, d, counts, num_negatives=n, seed=0,
        shared_negatives=shared, dtype=table_dtype or cfg["dtype"],
        compute_dtype=compute_dtype,
    )

    p = (counts / counts.sum()).astype(np.float64)
    if estimator in ("corpus", "corpus_subsample", "corpus_packed"):
        return _bench_corpus_mode(
            jax, eng, cfg, np, compute_dtype, p,
            subsample=(estimator == "corpus_subsample"),
            packed=(estimator == "corpus_packed"),
        )

    rng = np.random.default_rng(0)
    # Zipf-distributed center/context draws (the hot rows dominate, as in
    # real corpora after subsampling). One stacked group of spc minibatches,
    # dispatched as a single on-device lax.scan — the production hot path.
    centers_k = rng.choice(V, size=(spc, B), p=p).astype(np.int32)
    contexts_k = rng.choice(V, size=(spc, B, C), p=p).astype(np.int32)
    mask_k = (rng.random((spc, B, C)) < 0.85).astype(np.float32)
    # Measured mask density for useful-FLOPs accounting, taken from the
    # host copy BEFORE device_put: pulling the full mask back later
    # would re-pay the device->host transfer the device-input path
    # exists to avoid.
    density = float(mask_k.mean())
    alphas = np.full(spc, 0.025, np.float32)
    host_inputs = bool(int(os.environ.get("BENCH_HOST_INPUTS", "0")))
    if not host_inputs:
        # Device-resident inputs: production fit()/fit_file() assembles
        # batches ON device from the uploaded corpus (ops/device_batching),
        # so steady-state training ships only scalars per dispatch. Feeding
        # numpy here instead would re-measure a host->device transfer the
        # hot path no longer performs (the round-4 probe put that penalty
        # at ~6x over the axon tunnel: scan4 1334 -> 172 us/step).
        # BENCH_HOST_INPUTS=1 restores the old behavior as a diagnostic.
        centers_k, contexts_k, mask_k, alphas = map(
            jax.device_put, (centers_k, contexts_k, mask_k, alphas)
        )
        jax.block_until_ready(alphas)
    key = jax.random.PRNGKey(0)

    # Warm up / compile.
    t0 = time.time()
    losses = eng.train_steps(centers_k, contexts_k, mask_k, key, alphas, 0)
    jax.block_until_ready(losses)
    compile_s = time.time() - t0

    # Timed loop: run dispatches until the floor is reached so one number
    # is never a single-dispatch fluke.
    min_seconds = float(os.environ.get("BENCH_MIN_SECONDS", 2.0))
    max_calls = int(os.environ.get("BENCH_MAX_CALLS", 50))
    t0 = time.time()
    calls = 0
    last = None
    while calls < max_calls:
        last = eng.train_steps(
            centers_k, contexts_k, mask_k, key, alphas, calls * spc
        )
        calls += 1
        if calls >= 2 and time.time() - t0 >= min_seconds:
            break
    jax.block_until_ready(last)
    dt = time.time() - t0

    steps = calls * spc
    words = B * steps  # trained center positions == reference word count
    wps = words / dt
    flops = _flops_per_step(mode, cfg, density) * steps / dt
    del eng  # release the two V x d tables before the next mode runs
    return {
        "words_per_sec": round(wps, 1),
        # Useful-pair throughput + its dense-word normalization (see
        # module docstring): the grid-vs-packed comparable numbers.
        "pairs_per_sec": round(wps * C * density, 1),
        "effective_words_per_sec": round(wps * density, 1),
        "step_time_us": round(dt / steps * 1e6, 1),
        "compile_s": round(compile_s, 1),
        "flops_per_sec": round(flops, 3),
        "mask_density": round(density, 4),
        "timed_steps": steps,
        # Effective dtypes for THIS mode (suffixes override BENCH_DTYPE),
        # so the artifact is self-describing.
        "table_dtype": table_dtype or cfg["dtype"],
        "compute_dtype": compute_dtype,
        "inputs": "host" if host_inputs else "device",
    }


def _bench_corpus_mode(
    jax, eng, cfg, np, compute_dtype, p, subsample=False, packed=False,
):
    """The production fit/fit_file hot path: the flat Zipf corpus uploaded
    to HBM once, every minibatch assembled INSIDE the jitted train scan
    (ops/device_batching window shrinkage + sentence bounds); per-dispatch
    host->device traffic is scalars only. With ``subsample`` the per-epoch
    on-device subsample-compact pass runs first (the realistic production
    config) and training covers the compacted stream. With ``packed`` the
    scan runs the DENSE pair-packing dispatch (ISSUE 4): valid pairs
    compacted into batch*context_lanes pair slots per step — same nominal
    step FLOPs as a grid dispatch, ~1/density more corpus positions
    covered per step; its ``mask_density`` is the packed fill."""
    V, B, spc = cfg["vocab"], cfg["batch"], cfg["steps_per_call"]
    # Window sized so the device batcher's lane count (2W-3) matches the
    # context_lanes the FLOPs formula charges.
    W = (cfg["context_lanes"] + 3) // 2
    assert 2 * W - 3 == cfg["context_lanes"], cfg
    sent_len = 40
    rng = np.random.default_rng(0)
    N = max(4 * spc * B, 2_000_000)
    N -= N % sent_len
    ids = rng.choice(V, size=N, p=p).astype(np.int32)
    offsets = np.arange(0, N + sent_len, sent_len, dtype=np.int64)
    eng.upload_corpus(ids, offsets)
    ratio = cfg["subsample_ratio"]
    n_pos = N
    compact_s = None
    if subsample:
        # Per-word keep probabilities by the exact Vocabulary
        # .keep_probabilities rule; ``p`` IS the normalized frequency the
        # Zipf corpus was drawn from, so no vocab scan is needed.
        with np.errstate(divide="ignore", invalid="ignore"):
            kp = (np.sqrt(p / ratio) + 1.0) * (ratio / p)
        kp = np.clip(np.where(p > 0, kp, 0.0), 0.0, 1.0).astype(np.float32)
        eng.set_keep_probs(kp)
        eng.compact_corpus(jax.random.PRNGKey(1))  # compile warm-up
        t0 = time.time()
        n_pos = eng.compact_corpus(jax.random.PRNGKey(2))
        compact_s = time.time() - t0  # steady-state per-epoch cost
    alphas = np.full(spc, 0.025, np.float32)
    key = jax.random.PRNGKey(0)
    min_seconds = float(os.environ.get("BENCH_MIN_SECONDS", 2.0))
    max_calls = int(os.environ.get("BENCH_MAX_CALLS", 50))
    C = cfg["context_lanes"]
    pairs_done = None

    if packed:
        # Pair slots per step = the grid step's lane count, so one packed
        # dispatch costs the same nominal contraction FLOPs as one grid
        # dispatch; LR params pinned so alpha ~= the grid loop's 0.025.
        P = B * C
        pk = dict(
            step_size=0.025, total_words=10**12, words_base=0,
        )
        t0 = time.time()
        res = eng.train_steps_corpus_packed(
            0, P, W, B, key, spc, step0=0, grid_step0=0, **pk
        )
        jax.block_until_ready(res[0])
        compile_s = time.time() - t0

        t0 = time.time()
        calls, words, pairs_done, pos, live_slots = 0, 0, 0, 0, 0
        while calls < max_calls:
            if pos >= n_pos:
                pos = 0  # epoch wrap
            res = eng.train_steps_corpus_packed(
                pos, P, W, B, key, spc, step0=calls * spc, grid_step0=0,
                **pk,
            )
            # The (K,)-scalar readback the production loop also performs
            # per dispatch — the data-dependent position advance.
            pos_ends = np.asarray(res[2])
            pairs_done += int(np.asarray(res[1]).sum())
            # Fill denominator counts LIVE steps only (same rule as the
            # fit loop's packed_mask_density): steps past the corpus end
            # are zero-pair no-ops, and charging their empty slots would
            # understate the per-dispatch fill on epoch crossings.
            starts = np.concatenate(([pos], pos_ends[:-1]))
            live_slots += int((starts < n_pos).sum()) * P
            words += max(0, min(n_pos, int(pos_ends[-1])) - pos)
            pos = int(pos_ends[-1])
            calls += 1
            if calls >= 2 and time.time() - t0 >= min_seconds:
                break
        dt = time.time() - t0
        steps = calls * spc
    else:
        t0 = time.time()
        losses = eng.train_steps_corpus(0, B, W, key, alphas, 0)
        jax.block_until_ready(losses)
        compile_s = time.time() - t0

        span = max(n_pos - spc * B, 1)  # wrap: no dispatch hits the tail
        t0 = time.time()
        calls, last, words = 0, None, 0
        while calls < max_calls:
            start = (calls * spc * B) % span
            last = eng.train_steps_corpus(
                start, B, W, key, alphas, calls * spc
            )
            # Credit only LIVE positions: an aggressive ratio can compact
            # n_pos below one dispatch's coverage, and the tail rows past
            # n_pos are zero-mask no-ops that must not count as trained
            # words.
            words += max(0, min(n_pos, start + spc * B) - start)
            calls += 1
            if calls >= 2 and time.time() - t0 >= min_seconds:
                break
        jax.block_until_ready(last)
        dt = time.time() - t0
        steps = calls * spc

    # MEASURED mask density of the device-assembled windows (shrink draw
    # + sentence-bound clipping leave ~0.42 of the lanes live at W=5:
    # E[max(2b-1,0)]/7 = 0.457 for b~U[0,5), minus boundary loss):
    # evaluate the actual batcher on one step's B positions, reusing the
    # corpus the engine already holds on device — no re-upload, and only
    # a B*C mask comes back to host.
    from glint_word2vec_tpu.ops.device_batching import device_window_batch

    jnp = jax.numpy
    # Probe the ACTIVE corpus view (the compacted buffers when
    # subsampling): its shrunk-window density is what the scan executed.
    dev_ids, dev_offsets = (
        eng._corpus_compacted if subsample else eng._corpus
    )
    _, _, probe_mask = device_window_batch(
        dev_ids, dev_offsets,
        jnp.arange(B, dtype=jnp.int32),
        jnp.arange(B, dtype=jnp.int32),
        key, W,
        n_valid=jnp.int32(n_pos),
    )
    density = float(np.asarray(probe_mask).mean())
    del probe_mask
    if packed:
        # mask_density for the packed cell is the measured FILL of the
        # dense pair batches (live pairs / dispatched pair slots); the
        # grid density of the same corpus is echoed for context — it is
        # the waste packing removed. pairs/sec is measured directly.
        d_, n_ = cfg["dim"], cfg["negatives"]
        fill = pairs_done / max(live_slots, 1)
        out = {
            "words_per_sec": round(words / dt, 1),
            "pairs_per_sec": round(pairs_done / dt, 1),
            "effective_words_per_sec": round(pairs_done / dt / C, 1),
            "step_time_us": round(dt / steps * 1e6, 1),
            "compile_s": round(compile_s, 1),
            "flops_per_sec": round(
                (6.0 * d_ * (1 + n_) * pairs_done + words * d_) / dt, 3
            ),
            "mask_density": round(fill, 4),
            "grid_mask_density": round(density, 4),
            "pair_batch": B * C,
            "timed_steps": steps,
            "table_dtype": str(eng.syn0.dtype),
            "compute_dtype": compute_dtype,
            "corpus_words_device": int(N),
            "window": W,
            "inputs": "device_corpus_packed",
        }
        return out
    out = {
        "words_per_sec": round(words / dt, 1),
        "pairs_per_sec": round(words / dt * C * density, 1),
        "effective_words_per_sec": round(words / dt * density, 1),
        "step_time_us": round(dt / steps * 1e6, 1),
        "compile_s": round(compile_s, 1),
        "flops_per_sec": round(
            _flops_per_step("corpus", cfg, density) * steps / dt, 3
        ),
        "mask_density": round(density, 4),
        "timed_steps": steps,
        "table_dtype": str(eng.syn0.dtype),
        "compute_dtype": compute_dtype,
        "corpus_words_device": int(N),
        "window": W,
        "inputs": "device_corpus",
    }
    if subsample:
        # The effective ratio + what it kept, so the JSON line is
        # self-describing about what the words/sec number trained over.
        out["subsample_ratio"] = ratio
        out["corpus_words_kept"] = int(n_pos)
        out["kept_fraction"] = round(n_pos / N, 4)
        out["compact_s"] = round(compact_s, 3)
    return out


def worker_main() -> None:
    from glint_word2vec_tpu.utils.platform import force_platform

    # The env var alone is not enough under environments that pre-register
    # a remote TPU backend and pin jax_platforms at interpreter start.
    force_platform(os.environ.get("BENCH_PLATFORM"))
    import numpy as np
    import jax

    from glint_word2vec_tpu.parallel.mesh import make_mesh

    cfg = _config_from_env()
    dev = jax.devices()[0]
    mesh = make_mesh(1, 1, devices=[dev])

    modes = [m.strip() for m in cfg.pop("modes").split(",") if m.strip()]
    results = {}
    peaks = {}
    partial_path = os.environ.get("BENCH_PARTIAL")

    def _flush_partial():
        # Round-4 lesson: a worker killed mid-run (tunnel death, timeout)
        # lost every finished mode. Flush after each mode so the
        # orchestrator can salvage what DID complete.
        if not partial_path:
            return
        tmp = partial_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "platform": dev.platform,
                    "device_kind": dev.device_kind,
                    "config": cfg,
                    "modes": results,
                },
                f,
            )
        os.replace(tmp, partial_path)

    for mode in modes:
        _, compute_dtype, _ = _mode_parts(mode)
        peak = (
            _peak_for(dev.device_kind, compute_dtype)
            if dev.platform == "tpu" else None
        )
        try:
            r = _bench_mode(jax, mesh, cfg, mode, np)
        except Exception as e:  # keep the modes that DID finish (flaky
            results[mode] = {"error": f"{type(e).__name__}: {e}"}  # tunnel)
            _flush_partial()
            continue
        if peak and "flops_per_sec" in r:
            r["mfu"] = round(r.pop("flops_per_sec") / peak, 4)
            r["peak_flops_assumed"] = peak
            peaks[mode] = peak
        else:
            r.pop("flops_per_sec", None)
        results[mode] = r
        _flush_partial()

    ok = {m: r for m, r in results.items() if "words_per_sec" in r}
    if not ok or ("per_pair" in modes and "per_pair" not in ok):
        # Nothing measured — or the per_pair HEADLINE mode failed (its
        # number is what vs_baseline is calibrated against): fail the
        # worker so the orchestrator retries / falls back instead of
        # silently reporting a different estimator as the headline.
        raise RuntimeError(f"headline mode missing: {results}")
    headline_mode = "per_pair" if "per_pair" in ok else next(iter(ok))
    headline = ok[headline_mode]
    wps = headline["words_per_sec"]
    line = {
        "metric": "sgns_train_throughput",
        "value": wps,
        "unit": "words/sec/chip",
        "vs_baseline": round(wps / BASELINE_WORDS_PER_SEC_PER_CHIP, 4),
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "estimator": headline_mode,
        "config": cfg,
        "modes": results,
    }
    if headline_mode in peaks:
        line["peak_flops_assumed"] = peaks[headline_mode]
        if "mfu" in headline:
            line["mfu"] = headline["mfu"]
    print(json.dumps(line))
    sys.stdout.flush()


# ----------------------------------------------------------------------
# Orchestrator: subprocess + retry + CPU fallback + diagnostic line.
# ----------------------------------------------------------------------


def _run_worker(env, timeout):
    """Run one worker attempt; return (json_line_or_None, error_string)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, f"worker timed out after {timeout}s"
    for ln in reversed(proc.stdout.splitlines()):
        ln = ln.strip()
        if ln.startswith("{") and '"metric"' in ln:
            try:
                json.loads(ln)
                return ln, ""
            except json.JSONDecodeError:
                pass
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-6:]
    return None, f"rc={proc.returncode}: " + " | ".join(tail)


def _probe_backend(env, timeout):
    """Tiny-matmul liveness probe in a subprocess: is the accelerator
    backend reachable at all? Round 4 burned the full 900s attempt budget
    against a dead tunnel; this spends at most ``timeout`` (generous,
    because first device init over the tunnel can exceed 120s) before the
    orchestrator decides where the real attempts should go."""
    code = (
        "import os, sys\n"
        # python -c omits the script dir from sys.path; resolve the
        # package the same way the worker subprocess does.
        f"sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})\n"
        "from glint_word2vec_tpu.utils.platform import force_platform\n"
        "force_platform(os.environ.get('BENCH_PLATFORM'))\n"
        "import jax, jax.numpy as jnp\n"
        "x = jnp.ones((256, 256))\n"
        "assert float((x @ x).sum()) > 0\n"
        "print(jax.devices()[0].platform)\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, f"probe timed out after {timeout:g}s"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return None, f"probe rc={proc.returncode}: " + " | ".join(tail)
    return proc.stdout.strip().splitlines()[-1], ""


def _salvage_partial(paths, attempts, require_per_pair):
    """Build a result line from the workers' incremental per-mode flushes
    when every attempt died mid-run — finished modes are still evidence.

    ``paths`` are merged in order, a finished mode always winning over an
    error entry, so a retry attempt that only recorded errors cannot
    destroy the default attempt's completed measurements. When
    ``require_per_pair`` (the headline mode was requested), salvage
    declines unless per_pair itself finished — same protection the worker
    enforces by raising, so a non-comparable estimator never becomes the
    headline silently."""
    merged, meta = {}, {}
    for path in paths:
        try:
            with open(path) as f:
                part = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for m, r in part.get("modes", {}).items():
            if "words_per_sec" in r or m not in merged:
                merged[m] = r
        if part.get("modes") and not meta:
            meta = part
    ok = {m: r for m, r in merged.items() if "words_per_sec" in r}
    if not ok or (require_per_pair and "per_pair" not in ok):
        return None
    headline_mode = "per_pair" if "per_pair" in ok else next(iter(ok))
    wps = ok[headline_mode]["words_per_sec"]
    return {
        "metric": "sgns_train_throughput",
        "value": wps,
        "unit": "words/sec/chip",
        "vs_baseline": round(wps / BASELINE_WORDS_PER_SEC_PER_CHIP, 4),
        "platform": meta.get("platform"),
        "device_kind": meta.get("device_kind"),
        "estimator": headline_mode,
        "config": meta.get("config"),
        "modes": merged,
        "salvaged_partial": True,
        "failed_attempts": attempts,
    }


def main() -> None:
    timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", 600))
    base_env = dict(os.environ, BENCH_WORKER="1")
    # BENCH_PARTIAL is a base name; each attempt flushes to its own
    # suffixed path so a later attempt that records only errors can never
    # clobber an earlier attempt's completed modes.
    partial_base = os.environ.get("BENCH_PARTIAL", "/tmp/bench_partial.json")
    acc_paths = [partial_base + ".a1", partial_base + ".a2"]
    cpu_path = partial_base + ".cpu"
    for p in acc_paths + [cpu_path]:
        if os.path.exists(p):
            os.unlink(p)
    require_per_pair = "per_pair" in [
        m.strip() for m in _config_from_env()["modes"].split(",")
    ]

    attempts = []
    plans = [
        ("default", dict(base_env, BENCH_PARTIAL=acc_paths[0]), timeout),
        ("retry", dict(base_env, BENCH_PARTIAL=acc_paths[1]),
         min(timeout, 300.0)),
    ]
    if os.environ.get("BENCH_PLATFORM", "") != "cpu":
        probe_to = float(os.environ.get("BENCH_PROBE_TIMEOUT", 240))
        platform, err = _probe_backend(base_env, probe_to)
        if platform is None:
            # Dead backend: don't burn the attempt budget against it.
            attempts.append({"attempt": "liveness-probe", "error": err})
            plans = []
        # Separate partial path so a CPU fallback run never clobbers the
        # accelerator attempts' salvageable per-mode results.
        cpu_env = dict(base_env, BENCH_PLATFORM="cpu", BENCH_PARTIAL=cpu_path)
        # CPU can't hold/update two 1Mx300 tables fast enough to be a
        # meaningful number; shrink unless the caller pinned the shape.
        if "BENCH_VOCAB" not in os.environ:
            cpu_env["BENCH_VOCAB"] = "100000"
        if "BENCH_BATCH" not in os.environ:
            cpu_env["BENCH_BATCH"] = "1024"
        plans.append(("cpu-fallback", cpu_env, timeout))

    for name, env, t in plans:
        line, err = _run_worker(env, t)
        if line is not None:
            obj = json.loads(line)
            if name == "cpu-fallback":
                obj["fallback"] = "cpu"
            if len(attempts):
                obj["failed_attempts"] = attempts
            print(json.dumps(obj))
            return
        attempts.append({"attempt": name, "error": err[:500]})
        if name == "retry":
            # Both accelerator attempts died mid-run but the headline mode
            # finished in one of them: those numbers beat a CPU fallback.
            salvage = _salvage_partial(
                acc_paths, list(attempts), require_per_pair
            )
            if salvage is not None:
                print(json.dumps(salvage))
                return

    # Last resort: the CPU fallback's own partial (accelerator partials
    # were already salvaged after the retry attempt, and don't exist when
    # the probe emptied the plans).
    salvage = _salvage_partial([cpu_path], attempts, require_per_pair)
    if salvage is not None:
        salvage["fallback"] = "cpu"
        print(json.dumps(salvage))
        return
    print(
        json.dumps(
            {
                "metric": "sgns_train_throughput",
                "value": 0.0,
                "unit": "words/sec/chip",
                "vs_baseline": 0.0,
                "error": "all backend attempts failed",
                "failed_attempts": attempts,
            }
        )
    )


if __name__ == "__main__":
    if os.environ.get("BENCH_WORKER") == "1":
        worker_main()
    else:
        main()
