"""Benchmark: sustained SGNS training throughput on the available device.

Measures the fused train step (the dotprod+adjust equivalent) in steady
state on a realistic large-vocab configuration, reporting trained words per
second per chip. Baseline: the driver north-star of 50M words/sec on a
v5e-32 (BASELINE.json) = 1.5625M words/sec/chip; the reference itself
publishes no throughput numbers (BASELINE.md).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "words/sec/chip", "vs_baseline": N}

Environment knobs (for smoke-testing on CPU):
  BENCH_VOCAB, BENCH_DIM, BENCH_BATCH, BENCH_STEPS, BENCH_PLATFORM
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_WORDS_PER_SEC_PER_CHIP = 50e6 / 32


def main() -> None:
    if os.environ.get("BENCH_PLATFORM"):
        os.environ["JAX_PLATFORMS"] = os.environ["BENCH_PLATFORM"]
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    from glint_word2vec_tpu.parallel.engine import EmbeddingEngine
    from glint_word2vec_tpu.parallel.mesh import make_mesh

    V = int(os.environ.get("BENCH_VOCAB", 1_000_000))
    d = int(os.environ.get("BENCH_DIM", 300))
    B = int(os.environ.get("BENCH_BATCH", 8192))
    steps = int(os.environ.get("BENCH_STEPS", 30))
    C, n = 7, 5  # window=5 context lanes, 5 negatives (reference defaults)

    # Zipf-ish counts: realistic index skew for gathers and the noise table.
    ranks = np.arange(1, V + 1, dtype=np.float64)
    counts = np.maximum((1e9 / ranks), 1.0).astype(np.int64)

    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    eng = EmbeddingEngine(mesh, V, d, counts, num_negatives=n, seed=0)

    rng = np.random.default_rng(0)
    # Zipf-distributed center/context draws (the hot rows dominate, as in
    # real corpora after subsampling).
    p = (counts / counts.sum()).astype(np.float64)
    n_unique_batches = 8
    batches = []
    for _ in range(n_unique_batches):
        centers = rng.choice(V, size=B, p=p).astype(np.int32)
        contexts = rng.choice(V, size=(B, C), p=p).astype(np.int32)
        mask = (rng.random((B, C)) < 0.85).astype(np.float32)
        batches.append((centers, contexts, mask))

    key = jax.random.PRNGKey(0)
    # Warm up / compile.
    loss = eng.train_step(*batches[0], key, 0.025)
    jax.block_until_ready(loss)

    t0 = time.time()
    last = None
    for i in range(steps):
        c, x, m = batches[i % n_unique_batches]
        last = eng.train_step(c, x, m, jax.random.fold_in(key, i), 0.025)
    jax.block_until_ready(last)
    dt = time.time() - t0

    words = B * steps  # trained center positions == reference word count
    wps = words / dt
    print(
        json.dumps(
            {
                "metric": "sgns_train_throughput",
                "value": round(wps, 1),
                "unit": "words/sec/chip",
                "vs_baseline": round(wps / BASELINE_WORDS_PER_SEC_PER_CHIP, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
