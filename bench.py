"""Benchmark: sustained SGNS training throughput on the available device.

Measures the fused train step (the dotprod+adjust equivalent) in steady
state on a realistic large-vocab configuration, reporting trained words per
second per chip. Baseline: the driver north-star of 50M words/sec on a
v5e-32 (BASELINE.json) = 1.5625M words/sec/chip; the reference itself
publishes no throughput numbers (BASELINE.md).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "words/sec/chip", "vs_baseline": N}

Environment knobs (for smoke-testing on CPU):
  BENCH_VOCAB, BENCH_DIM, BENCH_BATCH, BENCH_STEPS, BENCH_PLATFORM,
  BENCH_SPC (minibatches per device dispatch — the scan length),
  BENCH_SHARED_NEG (shared noise-pool size; 0 = per-pair draws)
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_WORDS_PER_SEC_PER_CHIP = 50e6 / 32


def main() -> None:
    if os.environ.get("BENCH_PLATFORM"):
        os.environ["JAX_PLATFORMS"] = os.environ["BENCH_PLATFORM"]
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    from glint_word2vec_tpu.parallel.engine import EmbeddingEngine
    from glint_word2vec_tpu.parallel.mesh import make_mesh

    V = int(os.environ.get("BENCH_VOCAB", 1_000_000))
    d = int(os.environ.get("BENCH_DIM", 300))
    B = int(os.environ.get("BENCH_BATCH", 8192))
    steps = int(os.environ.get("BENCH_STEPS", 64))
    spc = int(os.environ.get("BENCH_SPC", 32))  # minibatches per dispatch
    # Shared noise-pool size (the TPU-shaped estimator; see
    # Word2VecParams.shared_negatives). 0 benches per-pair draws.
    shared = int(os.environ.get("BENCH_SHARED_NEG", 4096))
    C, n = 7, 5  # window=5 context lanes, 5 negatives (reference defaults)
    steps = (steps // spc) * spc or spc

    # Zipf-ish counts: realistic index skew for gathers and the noise table.
    ranks = np.arange(1, V + 1, dtype=np.float64)
    counts = np.maximum((1e9 / ranks), 1.0).astype(np.int64)

    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    eng = EmbeddingEngine(
        mesh, V, d, counts, num_negatives=n, seed=0,
        shared_negatives=shared,
    )

    rng = np.random.default_rng(0)
    # Zipf-distributed center/context draws (the hot rows dominate, as in
    # real corpora after subsampling). One stacked group of spc minibatches,
    # dispatched as a single on-device lax.scan (engine.train_steps) — the
    # production hot path of fit().
    p = (counts / counts.sum()).astype(np.float64)
    centers_k = rng.choice(V, size=(spc, B), p=p).astype(np.int32)
    contexts_k = rng.choice(V, size=(spc, B, C), p=p).astype(np.int32)
    mask_k = (rng.random((spc, B, C)) < 0.85).astype(np.float32)
    alphas = np.full(spc, 0.025, np.float32)

    key = jax.random.PRNGKey(0)
    # Warm up / compile.
    losses = eng.train_steps(centers_k, contexts_k, mask_k, key, alphas, 0)
    jax.block_until_ready(losses)

    t0 = time.time()
    last = None
    for g in range(steps // spc):
        last = eng.train_steps(
            centers_k, contexts_k, mask_k, key, alphas, g * spc
        )
    jax.block_until_ready(last)
    dt = time.time() - t0

    words = B * steps  # trained center positions == reference word count
    wps = words / dt
    print(
        json.dumps(
            {
                "metric": "sgns_train_throughput",
                "value": round(wps, 1),
                "unit": "words/sec/chip",
                "vs_baseline": round(wps / BASELINE_WORDS_PER_SEC_PER_CHIP, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
